"""High-concurrency serving: prepared-plan cache, result/subplan cache,
and batched status ingestion (scheduler/serving_cache.py, serving.py).

Covers the acceptance matrix of the serving work:

- plan/result cache hits on repeated SQL, bit-identical to uncached runs
  and to a caches-disabled session;
- invalidation on data change (file append to a path-backed table),
  table replacement, config change, and DDL (drop/re-register);
- >= 32 concurrent sessions against one scheduler with zero errors and a
  nonzero hit rate;
- batched status-report ingestion equivalent to per-event delivery;
- template reuse with AQE enabled (the template is pre-AQE; every run
  re-optimizes from its own shuffle stats).
"""
import threading

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig

CACHES_ON = {"ballista.plan.cache.enabled": "true",
             "ballista.result.cache.enabled": "true",
             "ballista.shuffle.partitions": "2"}
CACHES_OFF = {"ballista.plan.cache.enabled": "false",
              "ballista.result.cache.enabled": "false",
              "ballista.shuffle.partitions": "2"}

Q6ISH = ("select sum(b * c) as revenue from t "
         "where b > 0.02 and a < 30")
Q1ISH = ("select a % 4 as g, count(*) as n, sum(b) as s from t "
         "group by a % 4 order by g")


def _table(n=400, seed=7):
    import numpy as np

    rng = np.random.default_rng(seed)
    return pa.table({
        "a": pa.array(rng.integers(0, 40, n).astype(np.int64)),
        "b": pa.array(rng.uniform(0.0, 0.1, n)),
        "c": pa.array(rng.uniform(1.0, 100.0, n)),
    })


def _ctx(settings=CACHES_ON):
    ctx = BallistaContext.standalone(BallistaConfig(dict(settings)))
    ctx.register_table("t", _table())
    return ctx


def _caches(ctx):
    sched = ctx._standalone.scheduler
    return sched.plan_cache, sched.result_cache


# --------------------------------------------------------------------------
# hits + bit-identical results
# --------------------------------------------------------------------------


def test_repeat_query_hits_both_caches():
    ctx = _ctx()
    try:
        df1 = ctx.sql(Q6ISH).to_pandas()
        df2 = ctx.sql(Q6ISH).to_pandas()
        assert df1.equals(df2)
        pc, rc = _caches(ctx)
        assert pc.snapshot()["hits"] >= 1
        assert rc.snapshot()["hits"] >= 1
    finally:
        ctx.shutdown()


def test_cached_results_bit_identical_to_uncached():
    """q1/q6-shaped pair: the cached replay must byte-match both the first
    (uncached) run in the same session and a caches-disabled session."""
    on = _ctx(CACHES_ON)
    off = _ctx(CACHES_OFF)
    try:
        for sql in (Q6ISH, Q1ISH):
            first = on.sql(sql).to_pandas()   # planned + executed, captured
            cached = on.sql(sql).to_pandas()  # served from the result cache
            plain = off.sql(sql).to_pandas()
            assert first.equals(cached), sql
            assert plain.equals(cached), sql
            assert list(first.dtypes) == list(cached.dtypes), sql
        pc_off, rc_off = _caches(off)
        assert pc_off.snapshot()["hits"] == 0
        assert rc_off.snapshot()["hits"] == 0
    finally:
        on.shutdown()
        off.shutdown()


def test_different_literals_share_no_result_entry():
    ctx = _ctx()
    try:
        df1 = ctx.sql("select count(*) as n from t where a < 10").to_pandas()
        df2 = ctx.sql("select count(*) as n from t where a < 20").to_pandas()
        assert int(df1.n[0]) < int(df2.n[0])
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# invalidation matrix
# --------------------------------------------------------------------------


def test_invalidate_on_data_append(tmp_path):
    """Path-backed table: appending a file changes the resolved file list,
    so the table fingerprint rotates and both caches invalidate."""
    d = tmp_path / "pt"
    d.mkdir()
    pq.write_table(pa.table({"x": [1, 2, 3]}), d / "part-0.parquet")
    ctx = BallistaContext.standalone(BallistaConfig(dict(CACHES_ON)))
    try:
        ctx.register_parquet("pt", str(d))
        q = "select sum(x) as s from pt"
        assert int(ctx.sql(q).to_pandas().s[0]) == 6
        assert int(ctx.sql(q).to_pandas().s[0]) == 6  # cached
        pq.write_table(pa.table({"x": [10]}), d / "part-1.parquet")
        assert int(ctx.sql(q).to_pandas().s[0]) == 16
        pc, _rc = _caches(ctx)
        assert pc.snapshot()["invalidations"] >= 1
    finally:
        ctx.shutdown()


def test_invalidate_on_table_replace():
    ctx = _ctx()
    try:
        q = "select count(*) as n, sum(a) as s from t"
        before = ctx.sql(q).to_pandas()
        ctx.sql(q).to_pandas()  # populate the result cache
        ctx.register_table("t", pa.table({"a": [100, 200],
                                          "b": [0.5, 0.6],
                                          "c": [1.0, 2.0]}))
        after = ctx.sql(q).to_pandas()
        assert int(after.n[0]) == 2 and int(after.s[0]) == 300
        assert not before.equals(after)
    finally:
        ctx.shutdown()


def test_config_change_uses_separate_entry():
    """Templates embed physical-planning decisions, so a changed session
    config must plan its own template — never reuse the old one."""
    ctx = _ctx()
    try:
        df1 = ctx.sql(Q1ISH).to_pandas()
        ctx.sql("set ballista.shuffle.partitions = 3")
        df2 = ctx.sql(Q1ISH).to_pandas()
        # partition count changes float-summation order; values match to ulps
        assert df1.g.tolist() == df2.g.tolist()
        assert df1.n.tolist() == df2.n.tolist()
        assert df1.s.tolist() == pytest.approx(df2.s.tolist())
        pc, _ = _caches(ctx)
        snap = pc.snapshot()
        # one template per config fingerprint for the same text
        assert snap["misses"] >= 2
    finally:
        ctx.shutdown()


def test_invalidate_on_ddl_drop_and_reregister():
    ctx = _ctx()
    try:
        q = "select count(*) as n from t"
        n0 = int(ctx.sql(q).to_pandas().n[0])
        ctx.sql(q).to_pandas()
        ctx.deregister_table("t")
        with pytest.raises(Exception):
            ctx.sql(q).to_pandas()
        # re-register: a NEW provider generation — the stale entries keyed
        # on the dropped provider must not serve
        ctx.register_table("t", _table(n=123))
        assert int(ctx.sql(q).to_pandas().n[0]) == 123
        assert n0 != 123
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# subplan cache (leaf shuffle stages, standalone/shared-fs only)
# --------------------------------------------------------------------------


def test_subplan_reuse_across_different_final_stages():
    """Two queries with the same leaf group-by stage but different final
    shapes: the second pre-completes the leaf stage from cached bytes."""
    ctx = _ctx()
    try:
        a = ctx.sql("select a % 4 as g, sum(b) as s from t group by a % 4 "
                    "order by g").to_pandas()
        b = ctx.sql("select a % 4 as g, sum(b) as s from t group by a % 4 "
                    "order by s desc").to_pandas()
        assert sorted(a.s.tolist()) == pytest.approx(sorted(b.s.tolist()))
        _pc, rc = _caches(ctx)
        assert rc.snapshot()["subplan_hits"] >= 1
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# batched status ingestion
# --------------------------------------------------------------------------


def test_status_inbox_drained_after_jobs():
    ctx = _ctx()
    try:
        for _ in range(3):
            ctx.sql(Q1ISH).to_pandas()
        sched = ctx._standalone.scheduler
        with sched._status_lock:
            assert all(not v for v in sched._status_inbox.values())
    finally:
        ctx.shutdown()


def test_batched_status_equivalent_to_per_event_delivery():
    """Coalesced inbox (default) vs one TaskUpdating event per status (the
    legacy path, still used by tests/chaos): identical results."""
    from arrow_ballista_tpu.scheduler.scheduler import TaskUpdating

    default_ctx = _ctx()
    legacy_ctx = _ctx()
    try:
        sched = legacy_ctx._standalone.scheduler

        def per_event(executor_id, statuses):
            for st in statuses:
                sched._event_loop.post(TaskUpdating(executor_id, [st]))

        sched.update_task_status = per_event
        got_default = default_ctx.sql(Q1ISH).to_pandas()
        got_legacy = legacy_ctx.sql(Q1ISH).to_pandas()
        assert got_default.equals(got_legacy)
    finally:
        default_ctx.shutdown()
        legacy_ctx.shutdown()


def test_batched_launch_equivalent_to_per_task_launch():
    """One launch_tasks call per offer round (default) vs one call per
    task: identical results — batching is transport-only."""
    batched_ctx = _ctx()
    single_ctx = _ctx()
    try:
        sched = single_ctx._standalone.scheduler
        orig = sched.launcher

        class PerTaskLauncher:
            def launch_tasks(self, executor_id, tasks):
                for t in tasks:
                    orig.launch_tasks(executor_id, [t])

            def stop(self):
                orig.stop()

        sched.launcher = PerTaskLauncher()
        got_batched = batched_ctx.sql(Q1ISH).to_pandas()
        got_single = single_ctx.sql(Q1ISH).to_pandas()
        assert got_batched.equals(got_single)
    finally:
        batched_ctx.shutdown()
        single_ctx.shutdown()


# --------------------------------------------------------------------------
# AQE + template reuse
# --------------------------------------------------------------------------


def test_template_reuse_with_aqe_enabled():
    """The template captures the PRE-AQE plan; each bound run re-optimizes
    at stage boundaries from its own runtime stats."""
    ctx = BallistaContext.standalone(BallistaConfig(
        {**CACHES_ON, "ballista.aqe.enabled": "true",
         "ballista.result.cache.enabled": "false",
         "ballista.shuffle.partitions": "4"}))
    try:
        ctx.register_table("t", _table(n=2000))
        df1 = ctx.sql(Q1ISH).to_pandas()
        df2 = ctx.sql(Q1ISH).to_pandas()  # template hit, full re-execution
        assert df1.equals(df2)
        pc, _ = _caches(ctx)
        snap = pc.snapshot()
        assert snap["hits"] >= 1
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# concurrency stress: >= 32 sessions, one shared scheduler
# --------------------------------------------------------------------------


def test_32_session_stress_zero_errors():
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    sched = SchedulerNetService("127.0.0.1", 0,
                                config=BallistaConfig(dict(CACHES_ON)))
    sched.start()
    ex = None
    try:
        import tempfile

        ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                            work_dir=tempfile.mkdtemp(prefix="serving-test-"),
                            concurrent_tasks=4,
                            executor_id="serving-stress-0")
        ex.start()
        # shared catalog: all sessions resolve one provider, sharing
        # templates and result entries
        from arrow_ballista_tpu.catalog import MemoryTable

        sched.catalog.register(MemoryTable("t", _table(n=500)))

        queries = [Q6ISH, Q1ISH,
                   "select count(*) as n from t where a < 25"]
        errors = []
        results = {}
        lock = threading.Lock()

        def session(si):
            try:
                c = BallistaContext.remote("127.0.0.1", sched.port,
                                           BallistaConfig(dict(CACHES_ON)))
                try:
                    for k in range(3):
                        sql = queries[(si + k) % len(queries)]
                        df = c.sql(sql).to_pandas()
                        with lock:
                            prev = results.setdefault(sql, df)
                        assert prev.equals(df), f"divergent result for {sql}"
                finally:
                    c.shutdown()
            except Exception as e:  # noqa: BLE001 — collected + asserted
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=session, args=(i,), daemon=True)
                   for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:5]
        pc = sched.server.plan_cache.snapshot()
        rc = sched.server.result_cache.snapshot()
        assert pc["hits"] > 0
        assert rc["hits"] > 0
    finally:
        if ex is not None:
            ex.stop(notify=False)
        sched.stop()


# --------------------------------------------------------------------------
# observability surface
# --------------------------------------------------------------------------


def test_rest_and_prometheus_expose_cache_counters():
    import json
    import urllib.request

    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    svc = SchedulerNetService("127.0.0.1", 0, rest_port=0)
    svc.start()
    try:
        rp = svc.rest.port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rp}/api/plan-cache") as r:
            snap = json.loads(r.read())
        assert {"hits", "misses", "evictions", "invalidations"} <= set(snap)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rp}/api/result-cache") as r:
            snap = json.loads(r.read())
        assert "subplan_hits" in snap
        text = svc.server.metrics.gather()
        for fam in ("plan_cache_hits_total", "plan_cache_misses_total",
                    "result_cache_hits_total", "cache_evictions_total"):
            assert fam in text
    finally:
        svc.stop()


# --------------------------------------------------------------------------
# SQL normalization unit coverage
# --------------------------------------------------------------------------


def test_normalize_sql_binds_literals_keeps_limits():
    from arrow_ballista_tpu.scheduler.serving_cache import normalize_sql

    t1, p1 = normalize_sql("select * from t where a < 10 and s = 'x'")
    t2, p2 = normalize_sql("select * from t where a < 99 and s = 'y'")
    assert t1 == t2
    assert p1 != p2
    # LIMIT/OFFSET are structural: different limits are different plans
    l1, _ = normalize_sql("select a from t limit 5")
    l2, _ = normalize_sql("select a from t limit 6")
    assert l1 != l2


def test_parse_memo_reused_per_session():
    ctx = _ctx()
    try:
        ctx.sql(Q6ISH).to_pandas()
        memo_size = len(ctx._ast_memo)
        ctx.sql(Q6ISH).to_pandas()
        assert len(ctx._ast_memo) == memo_size
        assert memo_size >= 1
    finally:
        ctx.shutdown()
