"""PR 12 device observatory: JIT compile/retrace accounting, host<->device
transfer bytes, memory watermarks, and the stage-fusion advisor.

Four layers, matching how the observatory is built:

  1. ``observed_jit`` keying semantics tested directly (compile vs retrace
     vs cache hit; scalar weak-typing; static-arg value keys resolved for
     positional call sites; disabled mode counts nothing);
  2. transfer accounting through the two sanctioned materialization sites
     in models/batch.py, checked against hand-computed byte counts from
     the padding rules (``round_capacity``);
  3. scope attribution: device events recorded inside ``op_scope`` fold
     into the operator's MetricsSet (and from there into ``_op_entry``'s
     device_ms/host_ms split); ``task_scope`` snapshots become
     ``TaskStatus.device_stats`` and survive wire serde only when
     non-empty;
  4. end-to-end through a standalone cluster: a repeated identical query
     reports 0 new compiles (the shared_program + wrapper key-set reuse
     property), shape churn retraces, watermarks appear in stage
     summaries and EXPLAIN ANALYZE, and the advisor ranks candidates
     deterministically.
"""
import json

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu import serde
from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.models.batch import ColumnBatch, round_capacity
from arrow_ballista_tpu.models.schema import INT64, Field, Schema
from arrow_ballista_tpu.obs import device as dev
from arrow_ballista_tpu.obs.advisor import advise_report
from arrow_ballista_tpu.obs.profile import JobObservability
from arrow_ballista_tpu.obs.stats import device_summary
from arrow_ballista_tpu.ops.physical import MetricsSet
from arrow_ballista_tpu.scheduler.types import TaskId, TaskStatus
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(autouse=True)
def _observatory_on():
    """Every test starts from the default-on observatory; tests that flip
    the process switches get them restored."""
    dev.set_enabled(True)
    dev.set_watermarks(True)
    yield
    dev.set_enabled(True)
    dev.set_watermarks(True)


def _delta(before, after):
    return {k: after.get(k, 0) - before.get(k, 0) for k in after}


# --------------------------------------------------------------------------
# observed_jit keying
# --------------------------------------------------------------------------

def test_observed_jit_compile_retrace_hit_counts():
    import jax.numpy as jnp

    f = dev.observed_jit("test.add", lambda x: x + 1)
    before = dev.STATS.snapshot()
    f(jnp.arange(4))        # first key ever -> compile
    f(jnp.arange(4))        # repeat key -> cache hit
    f(jnp.arange(8))        # new shape -> retrace
    f(jnp.arange(8))        # repeat -> cache hit
    d = _delta(before, dev.STATS.snapshot())
    assert d["jit_compiles"] == 1
    assert d["jit_retraces"] == 1
    assert d["jit_cache_hits"] == 2
    assert d["jit_compile_time"] > 0


def test_observed_jit_scalar_weak_typing():
    """Plain Python scalars key by TYPE only — jax weak-types them, so a
    changed value does not retrace; a changed type does."""
    import jax.numpy as jnp

    f = dev.observed_jit("test.scale", lambda x, s: x * s)
    before = dev.STATS.snapshot()
    f(jnp.arange(4), 2)
    f(jnp.arange(4), 3)      # int again: same key -> hit, not retrace
    f(jnp.arange(4), 2.5)    # float: new key -> retrace
    d = _delta(before, dev.STATS.snapshot())
    assert d["jit_compiles"] == 1
    assert d["jit_retraces"] == 1
    assert d["jit_cache_hits"] == 1


def test_observed_jit_static_args_key_by_value_positionally():
    """static_argnames resolve to positions (via the signature) so the
    positional call sites in kernels.py key statics by VALUE."""
    import jax.numpy as jnp

    def take(x, n):
        return x[:n]

    f = dev.observed_jit("test.take", take, static_argnames=("n",))
    before = dev.STATS.snapshot()
    assert f(jnp.arange(8), 2).shape == (2,)   # compile
    assert f(jnp.arange(8), 3).shape == (3,)   # new static value -> retrace
    assert f(jnp.arange(8), 2).shape == (2,)   # repeat -> hit
    d = _delta(before, dev.STATS.snapshot())
    assert d["jit_compiles"] == 1
    assert d["jit_retraces"] == 1
    assert d["jit_cache_hits"] == 1


def test_alias_churn_flagged_statically_and_counted_at_runtime(tmp_path):
    """Static/runtime agreement: the alias-churn scenario the
    trace-key-stability lint predicts (batch-varying column names flowing
    into a static arg) is the same one the observatory counts as
    retraces — one per distinct alias set, under the same signature."""
    import textwrap

    import jax.numpy as jnp

    from arrow_ballista_tpu.analysis import run_lints

    # static half: the lint flags the churning tuple(b.columns) static
    fixture = tmp_path / "arrow_ballista_tpu" / "ops"
    fixture.mkdir(parents=True)
    (fixture / "packer.py").write_text(textwrap.dedent("""\
        from ..obs.device import observed_jit

        def pack_fn(cols, names):
            return tuple(cols[n] for n in names)

        pack = observed_jit("churn.pack", pack_fn,
                            static_argnames=("names",))

        def run(batches):
            out = []
            for b in batches:
                names = tuple(b.columns)
                out.append(pack(b.columns, names))
            return out
        """))
    found = run_lints(str(tmp_path), rule_names=["trace-key-stability"])
    assert len(found) == 1
    assert "'churn.pack'" in found[0].message

    # runtime half: the identical wrapper shape, driven with churning
    # name tuples — the observatory books a retrace per new alias set
    def pack_fn(cols, names):
        return tuple(cols[n] for n in names)

    pack = dev.observed_jit("churn.pack", pack_fn,
                            static_argnames=("names",))
    arr = jnp.arange(8)
    before = dev.STATS.snapshot()
    for names in (("a",), ("b",), ("c",)):
        pack({names[0]: arr}, names)
    d = _delta(before, dev.STATS.snapshot())
    assert d["jit_compiles"] == 1
    assert d["jit_retraces"] == 2  # one per churned alias set
    assert d["jit_cache_hits"] == 0


def test_observed_jit_decorator_form_and_disabled_mode():
    import jax.numpy as jnp

    @dev.observed_jit("test.deco")
    def g(x):
        return x - 1

    dev.set_enabled(False)
    before = dev.STATS.snapshot()
    assert int(g(jnp.arange(4))[1]) == 0       # still computes
    assert int(g(jnp.arange(16))[1]) == 0      # new shape, still no count
    d = _delta(before, dev.STATS.snapshot())
    assert all(v == 0 for v in d.values()), f"disabled mode counted: {d}"


# --------------------------------------------------------------------------
# transfer accounting (hand-computed against the padding rules)
# --------------------------------------------------------------------------

SCHEMA2 = Schema([Field("a", INT64), Field("b", INT64)])


def test_transfer_bytes_match_padded_layout():
    n = 1000
    cap = round_capacity(n)
    assert cap == 1024  # the fixture's arithmetic below assumes this
    data = {"a": np.arange(n, dtype=np.int64),
            "b": np.arange(n, dtype=np.int64)}
    with dev.task_scope() as acc:
        cb = ColumnBatch.from_numpy(SCHEMA2, data)
        cols, rows = cb.packed_numpy()
    assert rows == n
    v = acc.values
    # h2d: one transfer of (2 int64 columns + bool mask) at capacity
    assert v["h2d_transfers"] == 1
    assert v["h2d_bytes"] == 2 * cap * 8 + cap
    # d2h: one packed int64 buffer of (count word + 2 columns at capacity)
    assert v["d2h_transfers"] == 1
    assert v["d2h_bytes"] == (1 + 2 * cap) * 8
    np.testing.assert_array_equal(cols["a"], data["a"])


def test_task_scope_snapshot_and_watermarks():
    with dev.task_scope() as acc:
        dev.record_transfer("h2d", 64, 0.001)
    snap = acc.snapshot()
    assert snap["h2d_bytes"] == 64
    # entry + exit watermark samples at minimum
    assert snap["watermark_samples"] >= 2
    assert snap["host_mem_peak"] > 0  # ru_maxrss is always nonzero on Linux
    assert "device_mem_peak" in snap
    json.dumps(snap)  # wire-framing safe

    dev.set_enabled(False)
    with dev.task_scope() as acc2:
        pass
    assert acc2 is None, "disabled task_scope yields None (no serde keys)"


# --------------------------------------------------------------------------
# scope attribution into operator metrics
# --------------------------------------------------------------------------

class _Op:
    def __init__(self):
        self._m = MetricsSet()

    def metrics(self):
        return self._m


def test_op_scope_attributes_events_to_operator_metrics():
    import jax.numpy as jnp

    op = _Op()
    f = dev.observed_jit("test.attr", lambda x: x * 2)
    with dev.op_scope(op):
        f(jnp.arange(4))     # compile
        f(jnp.arange(16))    # retrace — attributed to THIS operator
        dev.record_transfer("h2d", 100, 0.25)
    mm = op.metrics().to_dict()
    assert mm["jit_compiles"] == 1
    assert mm["jit_retraces"] == 1
    assert mm["h2d_bytes"] == 100
    assert mm["h2d_time"] == 0.25

    # the _op_entry fold: *_time keys -> time_ms, transfer/compile time
    # -> host_ms, h2d/d2h bytes -> transfer_bytes
    from arrow_ballista_tpu.obs.stats import _op_entry

    entry = _op_entry("0", 0, op, mm)
    assert entry["compiles"] == 1 and entry["retraces"] == 1
    assert entry["transfer_bytes"] == 100
    assert entry["host_ms"] >= 250.0   # the recorded h2d_time alone
    assert entry["host_ms"] <= entry["time_ms"] + 1e-6
    assert entry["device_ms"] == pytest.approx(
        entry["time_ms"] - entry["host_ms"], abs=0.01)


def test_op_scope_disabled_is_shared_null_context():
    dev.set_enabled(False)
    op = _Op()
    assert dev.op_scope(op) is dev.op_scope(op), \
        "disabled op_scope must not allocate per call"


# --------------------------------------------------------------------------
# TaskStatus.device_stats: wire serde + stage folding
# --------------------------------------------------------------------------

def test_device_stats_serde_only_when_present():
    bare = TaskStatus(TaskId("job-1", 1, 0), "exec-1", "success")
    o = serde.status_to_obj(bare)
    assert "device_stats" not in o, \
        "disabled mode must add no TaskStatus wire keys"
    assert serde.status_from_obj(o).device_stats == {}

    full = TaskStatus(TaskId("job-1", 1, 1), "exec-1", "success",
                      device_stats={"jit_compiles": 3, "h2d_bytes": 17408,
                                    "device_mem_peak": 4096})
    o2 = serde.status_to_obj(full)
    assert o2["device_stats"]["h2d_bytes"] == 17408
    rt = serde.status_from_obj(json.loads(json.dumps(o2)))
    assert rt.device_stats == full.device_stats
    assert serde.status_to_obj(rt) == o2  # canonical round-trip stability


def test_device_summary_sums_counters_maxes_peaks_and_guards_attempts():
    class _Info:
        def __init__(self, ds, attempt=0, st_attempt=0):
            self.attempt = attempt
            self.status = TaskStatus(
                TaskId("j", 1, 0, task_attempt=st_attempt), "e", "success",
                device_stats=ds)

    class _Stage:
        task_infos = [
            _Info({"jit_compiles": 2, "device_mem_peak": 100}),
            _Info({"jit_compiles": 3, "device_mem_peak": 70}),
            # speculative loser: status attempt != info attempt -> excluded
            _Info({"jit_compiles": 99, "device_mem_peak": 999},
                  attempt=1, st_attempt=0),
        ]

    out = device_summary(_Stage())
    assert out["jit_compiles"] == 5
    assert out["device_mem_peak"] == 100


# --------------------------------------------------------------------------
# advisor (pure, synthetic report)
# --------------------------------------------------------------------------

def _tree_op(path, op, host_ms=0.0, device_ms=5.0, compiles=0, retraces=0,
             compile_time=0.0, transfer=0):
    return {
        "path": path, "depth": path.count("."), "op": op, "label": op,
        "rows": 10, "time_ms": host_ms + device_ms, "bytes": 0,
        "device_ms": device_ms, "host_ms": host_ms,
        "transfer_bytes": transfer, "compiles": compiles,
        "retraces": retraces,
        "metrics": {"jit_compile_time": compile_time},
    }


def _synthetic_report():
    return {
        "job_id": "job-syn", "state": "successful", "wall_time_ms": 500.0,
        "stages": [
            {"stage_id": 1, "operator_tree": [
                _tree_op("0", "ShuffleWriterExec", host_ms=1.0),
                _tree_op("0.0", "ProjectionExec", host_ms=2.0,
                         compiles=1, retraces=3, compile_time=0.4),
                _tree_op("0.0.0", "FilterExec", host_ms=40.0, transfer=512),
                _tree_op("0.0.0.0", "ScanExec", host_ms=10.0),
            ]},
            {"stage_id": 2, "operator_tree": [
                _tree_op("0", "HashAggregateExec", host_ms=1.0),
                _tree_op("0.0", "ShuffleReaderExec", host_ms=50.0),
            ]},
        ],
    }


def test_advisor_chains_rank_and_schema():
    advice = advise_report(_synthetic_report())
    assert advice["job_id"] == "job-syn"
    assert advice["generated_from"] == "explain_analyze"
    cands = advice["candidates"]
    # stage 2's only chain head is unfusable-adjacent: HashAggregate ->
    # ShuffleReader never fuses, so only stage 1's chain survives
    assert len(cands) == 1
    c = cands[0]
    assert c["operators"] == ["ProjectionExec", "FilterExec", "ScanExec"]
    # est savings = downstream host_ms (40+10) + head retrace share
    # (400 ms compile time * 3/(1+3))
    assert c["est_savings_ms"] == pytest.approx(50.0 + 300.0)
    assert c["transfer_bytes"] == 512
    assert c["retraces"] == 3
    assert c["reasons"]
    assert advice["total_est_savings_ms"] == c["est_savings_ms"]
    assert "FUSION ADVISOR" in advice["text"]
    json.dumps(advice)


def test_advisor_deterministic_and_min_savings_filter():
    r = _synthetic_report()
    a1, a2 = advise_report(r), advise_report(r)
    assert a1 == a2, "equal inputs must produce identical advice"
    filtered = advise_report(r, min_savings_ms=10_000.0)
    assert filtered["candidates"] == []
    assert "no operator chain" in filtered["text"]


# --------------------------------------------------------------------------
# failover trace continuity (obs/profile.py adoption hooks)
# --------------------------------------------------------------------------

def test_adoption_continues_original_trace():
    obs = JobObservability()
    obs.on_submitted("job-f")
    parent = obs.task_parent("job-f")
    orig_trace = parent["trace_id"]

    # the adopting shard receives the checkpointed graph.trace and must
    # keep the SAME trace_id so both shards land on one Chrome timeline
    obs2 = JobObservability()
    obs2.on_adopted("job-f", epoch=7, prev_owner="shard-0",
                    scheduler_id="shard-1", trace=parent)
    adopted_parent = obs2.task_parent("job-f")
    assert adopted_parent["trace_id"] == orig_trace
    profile = obs2.get_profile("job-f")
    assert profile["trace_id"] == orig_trace
    assert "adoption@7" in profile["phases"], \
        "the adoption marker must annotate the fencing epoch"
    # without the checkpointed context, adoption starts a fresh trace
    obs3 = JobObservability()
    obs3.on_adopted("job-g", epoch=1)
    assert obs3.task_parent("job-g")["trace_id"] != orig_trace


def test_stand_down_closes_spans_and_keeps_profile():
    obs = JobObservability()
    obs.on_submitted("job-s")
    obs.on_stand_down("job-s", "lease lost to shard-9")
    prof = obs.profiles.get("job-s")
    assert prof is not None
    assert prof["state"] == "stood-down"
    assert prof["stand_down_reason"] == "lease lost to shard-9"
    spans = obs.profiles.get_spans("job-s")
    assert any(s.name == "lease stand-down" for s in spans)
    assert all(s.end_ms for s in spans), "stand-down must close every span"


# --------------------------------------------------------------------------
# end-to-end (standalone cluster)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ctx():
    c = BallistaContext.standalone(
        BallistaConfig({"ballista.shuffle.partitions": "4"}),
        concurrent_tasks=2, num_executors=2)
    rng = np.random.default_rng(11)
    n = 2000
    c.register_table("lineitem", pa.table({
        "okey": pa.array(rng.integers(0, 200, n), type=pa.int64()),
        "flag": pa.array(rng.integers(0, 3, n), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
        "price": pa.array(rng.random(n) * 1000, type=pa.float64()),
    }))
    c.register_table("orders", pa.table({
        "okey": pa.array(np.arange(200), type=pa.int64()),
        "cust": pa.array(np.arange(200) % 17, type=pa.int64()),
    }))
    yield c
    c.shutdown()


Q1 = ("select flag, sum(qty) as sq, sum(price) as sp, count(*) as c "
      "from lineitem where qty < 45 group by flag order by flag")


def test_repeated_query_reports_zero_new_compiles(ctx):
    ctx.sql(Q1).to_pandas()            # warm: compiles + retraces happen here
    before = dev.STATS.snapshot()
    ctx.sql(Q1).to_pandas()            # identical plan + identical shapes
    d = _delta(before, dev.STATS.snapshot())
    assert d["jit_compiles"] == 0 and d["jit_retraces"] == 0, (
        f"identical re-run must be all cache hits, got {d}")
    assert d["jit_cache_hits"] > 0
    assert d["program_cache_hits"] > 0, \
        "second run must reuse the shared_program closures"


def test_shape_churn_is_counted_as_retraces(ctx):
    ctx.sql(Q1).to_pandas()
    before = dev.STATS.snapshot()
    # a changed output alias changes the packed-column static key through
    # the ONE module-level pack_for_host wrapper — a retrace, not a fresh
    # compile, because that wrapper already traced q1's layouts
    ctx.sql("select flag, sum(qty) as churn_total from lineitem "
            "group by flag order by flag").to_pandas()
    d = _delta(before, dev.STATS.snapshot())
    assert d["jit_retraces"] > 0, \
        f"key churn through shared wrappers must count retraces: {d}"


def test_explain_analyze_carries_device_evidence(ctx):
    report = ctx.explain_analyze(Q1)
    assert report["state"] == "successful"
    saw_device_stage = saw_op_fields = saw_watermark = False
    for st in report["stages"]:
        devd = st.get("device") or {}
        if devd.get("h2d_bytes") or devd.get("d2h_bytes"):
            saw_device_stage = True
        if devd.get("device_mem_peak", 0) > 0:
            saw_watermark = True
        for op in st["operator_tree"]:
            assert {"device_ms", "host_ms", "transfer_bytes",
                    "compiles", "retraces"} <= set(op)
            if op["compiles"] or op["transfer_bytes"]:
                saw_op_fields = True
    assert saw_device_stage, "some stage must record transfer bytes"
    assert saw_op_fields, "some operator must attribute compiles/transfers"
    assert saw_watermark, "watermarks must fold into stage device summaries"
    json.dumps(report)


def test_advisor_end_to_end_ranks_a_candidate(ctx):
    # a COLD q18-shaped join+aggregate: first execution pays real compile
    # time, so fusion candidates clear the configured min-savings threshold
    advice = ctx.advise(
        "select o.cust, sum(l.qty) as total, count(*) as c "
        "from lineitem l join orders o on l.okey = o.okey "
        "where l.qty < 48 group by o.cust order by total desc")
    assert advice["candidates"], \
        "a cold join+aggregate must rank at least one fusion candidate"
    top = advice["candidates"][0]
    assert len(top["operators"]) >= 2
    assert top["est_savings_ms"] >= advice["candidates"][-1]["est_savings_ms"]
    assert top["est_savings_ms"] >= advice["min_savings_ms"]
    assert advice["text"].count("fuse") >= 1
    # the warm path stays schema-stable and deterministic even when the
    # threshold filters everything out
    a1, a2 = ctx.advise(Q1), ctx.advise(Q1)
    assert [c["operators"] for c in a1["candidates"]] \
        == [c["operators"] for c in a2["candidates"]]
