"""JSON + Avro readers and the get_file_metadata RPC.

Parity: reference register_json/register_avro (client context.rs:358-530)
and SchedulerGrpc.get_file_metadata (grpc.rs:271-325).  The avro codec is
home-grown (utils/avro.py) since no avro library ships in this image — the
round-trip tests double as its correctness suite.
"""
import json

import numpy as np
import pandas as pd
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.avro import avro_to_arrow, read_avro, write_avro
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

AVRO_SCHEMA = {
    "type": "record",
    "name": "row",
    "fields": [
        {"name": "k", "type": "long"},
        {"name": "v", "type": "double"},
        {"name": "s", "type": "string"},
        {"name": "maybe", "type": ["null", "long"]},
        {"name": "flag", "type": "boolean"},
    ],
}


def _rows(n=500, seed=4):
    rng = np.random.default_rng(seed)
    return [{
        "k": int(rng.integers(0, 7)),
        "v": float(rng.random()),
        "s": str(rng.choice(["x", "y", "z"])),
        "maybe": None if rng.random() < 0.2 else int(rng.integers(0, 100)),
        "flag": bool(rng.integers(0, 2)),
    } for _ in range(n)]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro_roundtrip(tmp_path, codec):
    rows = _rows()
    p = tmp_path / "data.avro"
    write_avro(str(p), AVRO_SCHEMA, rows, codec=codec)
    schema, back = read_avro(str(p))
    assert schema["fields"][0]["name"] == "k"
    assert back == rows


def test_avro_to_arrow_types(tmp_path):
    rows = _rows(50)
    p = tmp_path / "data.avro"
    write_avro(str(p), AVRO_SCHEMA, rows)
    t = avro_to_arrow(str(p))
    assert t.num_rows == 50
    assert str(t.schema.field("k").type) == "int64"
    assert str(t.schema.field("v").type) == "double"
    assert t.column("maybe").null_count == sum(1 for r in rows if r["maybe"] is None)


def test_register_avro_sql(tmp_path):
    rows = _rows(2000)
    write_avro(str(tmp_path / "a.avro"), AVRO_SCHEMA, rows, codec="deflate")
    ctx = BallistaContext.local()
    try:
        ctx.register_avro("t", str(tmp_path / "a.avro"))
        got = ctx.sql("SELECT k, COUNT(*) AS c, SUM(v) AS sv FROM t "
                      "GROUP BY k ORDER BY k").to_pandas()
    finally:
        ctx.shutdown()
    df = pd.DataFrame(rows)
    want = df.groupby("k", as_index=False).agg(c=("v", "size"), sv=("v", "sum"))
    assert got["c"].tolist() == want["c"].tolist()
    np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)


def test_register_json_sql(tmp_path):
    rng = np.random.default_rng(9)
    rows = [{"g": int(rng.integers(0, 4)), "x": float(rng.random())}
            for _ in range(1500)]
    p = tmp_path / "data.json"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    ctx = BallistaContext.local()
    try:
        ctx.register_json("j", str(p))
        got = ctx.sql("SELECT g, SUM(x) AS sx FROM j GROUP BY g ORDER BY g").to_pandas()
    finally:
        ctx.shutdown()
    want = pd.DataFrame(rows).groupby("g", as_index=False).agg(sx=("x", "sum"))
    np.testing.assert_allclose(got["sx"], want["sx"], rtol=1e-9)


def test_get_file_metadata_rpc(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from arrow_ballista_tpu.net import wire
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    pq.write_table(pa.table({"a": [1, 2], "b": ["x", "y"]}),
                   str(tmp_path / "f.parquet"))
    write_avro(str(tmp_path / "f.avro"), AVRO_SCHEMA, _rows(5))
    sched = SchedulerNetService("127.0.0.1", 0, rest_port=None)
    sched.start()
    try:
        out, _ = wire.call("127.0.0.1", sched.port, "get_file_metadata",
                           {"path": str(tmp_path / "f.parquet")})
        assert out["format"] == "parquet"
        assert [f["name"] for f in out["schema"]] == ["a", "b"]
        out, _ = wire.call("127.0.0.1", sched.port, "get_file_metadata",
                           {"path": str(tmp_path / "f.avro")})
        assert out["format"] == "avro"
        assert [f["name"] for f in out["schema"]][:2] == ["k", "v"]
    finally:
        sched.stop()


def test_avro_through_remote_context(tmp_path):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    rows = _rows(800)
    write_avro(str(tmp_path / "t.avro"), AVRO_SCHEMA, rows)
    sched = SchedulerNetService("127.0.0.1", 0, rest_port=None)
    sched.start()
    ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                        work_dir=str(tmp_path / "w"))
    ex.start()
    try:
        ctx = BallistaContext.remote("127.0.0.1", sched.port)
        ctx.register_avro("t", str(tmp_path / "t.avro"))
        got = ctx.sql("SELECT COUNT(*) AS c FROM t WHERE flag").to_pandas()
        ctx.shutdown()
        assert got["c"].tolist() == [sum(1 for r in rows if r["flag"])]
    finally:
        ex.stop(notify=False)
        sched.stop()


def test_nyctaxi_benchmark_harness(tmp_path):
    """The nyctaxi harness (reference benchmarks/src/bin/nyctaxi.rs) runs
    end to end: synthesize tripdata, run fare_amt_by_passenger."""
    import json
    import subprocess
    import sys

    env = {**__import__("os").environ,
           "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}
    gen = subprocess.run(
        [sys.executable, "-m", "benchmarks.nyctaxi", "generate",
         "--rows", "20000", "--output", str(tmp_path)],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT, env=env)
    assert gen.returncode == 0, gen.stderr[-1500:]
    run = subprocess.run(
        [sys.executable, "-m", "benchmarks.nyctaxi", "benchmark",
         "--path", str(tmp_path), "--iterations", "1"],
        capture_output=True, text=True, timeout=600, cwd=REPO_ROOT, env=env)
    assert run.returncode == 0, run.stderr[-1500:]
    out = json.loads(run.stdout.strip().splitlines()[-1])
    assert out["results"]["fare_amt_by_passenger"]["min_ms"] > 0
