"""Job checkpoint/resume: graph serde round-trip + scheduler adoption.

Parity: SURVEY.md §5 checkpoint/resume — the reference persists the
ExecutionGraph protobuf on every transition so another scheduler can
decode and resume; shuffle files are the data checkpoints.  Completed
stages must NOT re-run after recovery.
"""
import time

import pytest

from arrow_ballista_tpu import serde
from arrow_ballista_tpu.scheduler.execution_graph import (
    RUNNING,
    SUCCESSFUL,
    ExecutionGraph,
)
from arrow_ballista_tpu.scheduler.persistence import FileJobStateBackend
from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig, SchedulerServer
from arrow_ballista_tpu.scheduler.types import ExecutorMetadata

from .test_scheduler import VirtualTaskLauncher, fake_success, physical_plan


def half_run_graph():
    """Stage 1 complete, stage 2 started (one in-flight task)."""
    graph = ExecutionGraph.build("jobx", physical_plan(partitions=3))
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("exec-A")
        graph.update_task_status([fake_success(t, "exec-A")])
    t2 = graph.pop_next_task("exec-A")
    assert t2 is not None and t2.task.stage_id == 2
    return graph


def test_graph_serde_roundtrip_preserves_progress():
    graph = half_run_graph()
    obj = serde.graph_to_obj(graph)
    back = serde.graph_from_obj(obj)
    assert back.job_id == "jobx" and back.status == "running"
    assert back.stages[1].state == SUCCESSFUL
    assert back.stages[1].outputs.keys() == graph.stages[1].outputs.keys()
    assert back.stages[2].state == RUNNING
    # in-flight task slots are NOT persisted -> re-issued after recovery
    assert all(t is None or t.state == "success"
               for t in back.stages[2].task_infos)
    # the recovered graph drains to completion without touching stage 1
    from .test_scheduler import drain

    stage1_tasks = []

    def hook(task):
        if task.task.stage_id == 1:
            stage1_tasks.append(task)
        return None

    drain(back, "exec-B", hook=hook)
    assert back.status == "successful"
    assert not stage1_tasks, "completed stage 1 must not re-run"


def test_file_backend_save_load_acquire(tmp_path):
    backend = FileJobStateBackend(str(tmp_path))
    graph = half_run_graph()
    backend.save_job(graph)
    assert backend.list_jobs() == ["jobx"]
    loaded = backend.load_job("jobx")
    assert loaded.stages[1].state == SUCCESSFUL

    assert backend.try_acquire_job("jobx", "sched-1")
    assert backend.try_acquire_job("jobx", "sched-1"), "re-acquire by owner"
    assert not backend.try_acquire_job("jobx", "sched-2"), "held by sched-1"
    # stale lock takeover
    assert backend.try_acquire_job("jobx", "sched-2", stale_after_s=0.0)

    backend.remove_job("jobx")
    assert backend.list_jobs() == []


def test_scheduler_adopts_persisted_job(tmp_path):
    backend = FileJobStateBackend(str(tmp_path))
    graph = half_run_graph()
    backend.save_job(graph)

    launcher = VirtualTaskLauncher()
    server = SchedulerServer(launcher, SchedulerConfig(), job_backend=backend,
                             scheduler_id="sched-new")
    launcher.scheduler = server
    server.init(start_reaper=False)
    server.register_executor(ExecutorMetadata("exec-B", task_slots=4))
    adopted = server.recover_jobs()
    assert adopted == ["jobx"]
    status = server.wait_for_job("jobx", 30)
    assert status.state == "successful"
    # stage 1 already complete: only stage 2+ tasks may launch
    assert all(t.task.stage_id != 1 for _, t in launcher.launched)
    # terminal state checkpointed
    assert backend.load_job("jobx").status == "successful"
    server.shutdown()
