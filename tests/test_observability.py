"""Observability: REST API, prometheus metrics, dot export."""
import json
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    sched = SchedulerNetService("127.0.0.1", 0, rest_port=0)
    sched.start()
    ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                        work_dir=str(tmp_path_factory.mktemp("obs")),
                        executor_id="obs-exec", metrics_port=0)
    ex.start()
    ctx = BallistaContext.remote("127.0.0.1", sched.port)
    ctx.register_table("t", pa.table({
        "g": pa.array(np.arange(1000) % 7, type=pa.int64()),
        "v": pa.array(np.arange(1000), type=pa.int64()),
    }))
    yield sched, ex, ctx
    ex.stop(notify=False)
    sched.stop()


def _get(sched, path, as_json=True):
    url = f"http://127.0.0.1:{sched.rest.port}{path}"
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read().decode()
    return json.loads(body) if as_json else body


def test_rest_state_and_executors(stack):
    sched, ex, ctx = stack
    state = _get(sched, "/api/state")
    assert state["executors"] == 1 and state["alive_executors"] == 1
    executors = _get(sched, "/api/executors")
    assert executors[0]["executor_id"] == "obs-exec"
    assert executors[0]["status"] == "active"


def test_rest_jobs_stages_dot_metrics(stack):
    sched, ex, ctx = stack
    out = ctx.sql("select g, sum(v) as s from t group by g order by g").to_pandas()
    assert len(out) == 7

    jobs = _get(sched, "/api/jobs")
    done = [j for j in jobs if j["state"] == "successful"]
    assert done, jobs
    job_id = done[0]["job_id"]
    assert done[0]["tasks_completed"] == done[0]["tasks_total"] > 0

    stages = _get(sched, f"/api/job/{job_id}/stages")
    assert len(stages) >= 2
    assert all(s["state"] == "successful" for s in stages)
    assert "ShuffleWriterExec" in stages[0]["plan"]

    dot = _get(sched, f"/api/job/{job_id}/dot", as_json=False)
    assert dot.startswith("digraph") and "shuffle" in dot

    metrics = _get(sched, "/api/metrics", as_json=False)
    assert "job_submitted_total" in metrics
    assert "job_exec_time_seconds_count" in metrics
    submitted = [l for l in metrics.splitlines()
                 if l.startswith("job_submitted_total")][0]
    assert int(submitted.split()[-1]) >= 1


def test_rest_cancel_patch(stack):
    sched, ex, ctx = stack
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{sched.rest.port}/api/job/nonexistent",
        method="PATCH")
    with urllib.request.urlopen(req, timeout=10) as r:
        body = json.loads(r.read().decode())
    assert body["cancelled"] == "nonexistent"


def test_web_ui_served(stack):
    """The dashboard page is served at / and references the API it polls
    (reference: React UI over the same /api endpoints, ui/src/*)."""
    sched, ex, ctx = stack
    html = _get(sched, "/", as_json=False)
    assert "<!doctype html>" in html.lower()
    for marker in ("/api/state", "/api/executors", "/api/jobs",
                   "Ballista-TPU Scheduler"):
        assert marker in html
    assert _get(sched, "/ui", as_json=False) == html


def test_keda_scaler_endpoint(stack):
    """KEDA external-scaler shape (reference external_scaler.rs:14-60)."""
    sched, ex, ctx = stack
    out = _get(sched, "/api/scaler")
    assert "inflight_tasks" in out and isinstance(out["inflight_tasks"], int)


def _run_job(sched, ctx, sql="select g, sum(v) as s from t group by g"):
    """Run a query through the remote stack and return its job id."""
    ctx.sql(sql).to_pandas()
    jobs = [j for j in _get(sched, "/api/jobs") if j["state"] == "successful"]
    assert jobs
    return jobs[-1]["job_id"]


def test_job_profile_endpoint(stack):
    """GET /api/job/<id>/profile: per-stage -> per-task -> per-operator
    breakdown for a completed multi-stage query (acceptance criterion)."""
    sched, ex, ctx = stack
    job_id = _run_job(sched, ctx,
                      "select g, sum(v) as s from t group by g order by g")
    prof = _get(sched, f"/api/job/{job_id}/profile")
    assert prof["job_id"] == job_id and prof["state"] == "successful"
    assert prof["trace_id"] and prof["wall_time_ms"] > 0
    assert set(prof["phases"]) == {"admission", "planning", "execution"}
    assert len(prof["stages"]) >= 2  # group-by + order-by force shuffles
    op_names = set()
    for stage in prof["stages"]:
        assert stage["state"] == "successful"
        # per-stage aggregated operator metrics, keyed by plan path
        assert any(k.endswith("ShuffleWriterExec")
                   for k in stage["operators"])
        assert stage["tasks"], stage
        for task in stage["tasks"]:
            assert task["state"] == "success"
            assert task["executor_id"] == "obs-exec"
            # per-task span tree: at least the stage's shuffle writer
            assert task["operators"], task
            for op in task["operators"]:
                assert op["duration_ms"] >= 0
                op_names.add(op["op"])
            # cumulative per-operator metric snapshot rides along too
            assert task["metrics"]
    assert "ShuffleWriterExec" in op_names
    assert {"HashAggregateExec", "SortExec"} & op_names
    # unknown jobs 404
    with pytest.raises(urllib.request.HTTPError):
        _get(sched, "/api/job/zzzzzzz/profile")


def test_job_trace_endpoint_chrome_schema_and_coverage(stack):
    """GET /api/job/<id>/trace: valid Chrome trace-event JSON whose spans
    cover >= 95% of the job's wall time (acceptance criterion)."""
    sched, ex, ctx = stack
    job_id = _run_job(sched, ctx)
    trace = _get(sched, f"/api/job/{job_id}/trace")
    assert trace["traceId"]
    events = trace["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    assert xs and metas
    # schema: every X event is a complete event with numeric us timing
    for e in xs:
        assert isinstance(e["name"], str) and e["cat"]
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] > 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert "span_id" in e["args"]
    # named processes for scheduler + executor
    pnames = {e["args"]["name"] for e in metas
              if e["name"] == "process_name"}
    assert "scheduler" in pnames and "executor obs-exec" in pnames
    # operator spans propagated back from the executor share the trace
    assert any(e["cat"] == "operator" for e in xs)
    # coverage: union of span intervals vs the root job span
    root = next(e for e in xs if e["name"] == f"job {job_id}")
    lo, hi = root["ts"], root["ts"] + root["dur"]
    covered, cur = 0.0, None
    for a, b in sorted((e["ts"], e["ts"] + e["dur"]) for e in xs):
        a, b = max(a, lo), min(b, hi)
        if b <= a:
            continue
        if cur is None or a > cur[1]:
            if cur is not None:
                covered += cur[1] - cur[0]
            cur = [a, b]
        else:
            cur[1] = max(cur[1], b)
    if cur is not None:
        covered += cur[1] - cur[0]
    assert covered / (hi - lo) >= 0.95


def test_dot_metric_annotations(stack):
    """The graphviz DAG carries per-operator rows/time labels once task
    metrics are in (flame-view satellite)."""
    sched, ex, ctx = stack
    job_id = _run_job(sched, ctx)
    dot = _get(sched, f"/api/job/{job_id}/dot", as_json=False)
    assert "rows" in dot and "ms" in dot


def test_executor_metrics_and_health_endpoint(stack):
    """Executor-side prometheus /metrics + /health listener satellite."""
    sched, ex, ctx = stack
    _run_job(sched, ctx)
    port = ex.obs_http.port
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        body = r.read().decode()
    for name in ("executor_tasks_launched_total",
                 "executor_tasks_completed_total",
                 "executor_tasks_failed_total",
                 "executor_tasks_killed_total",
                 "executor_shuffle_bytes_written_total",
                 "executor_active_tasks",
                 "executor_task_duration_seconds_count"):
        assert name in body, name
    completed = [l for l in body.splitlines()
                 if l.startswith("executor_tasks_completed_total ")][0]
    assert int(completed.split()[-1]) >= 1
    written = [l for l in body.splitlines()
               if l.startswith("executor_shuffle_bytes_written_total ")][0]
    assert int(written.split()[-1]) > 0
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/health",
                                timeout=10) as r:
        health = json.loads(r.read().decode())
    assert health["status"] == "ok"
    assert health["executor_id"] == "obs-exec"
    assert isinstance(health["active_tasks"], int)
    with pytest.raises(urllib.request.HTTPError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=10)


def test_span_propagation_remote_path(stack):
    """Task/operator spans produced in the executor cross the wire with
    the status update and land in the scheduler's trace, parented on the
    job's execution span."""
    sched, ex, ctx = stack
    job_id = _run_job(sched, ctx)
    spans = sched.server.obs.profiles.get_spans(job_id)
    assert spans is not None
    by_id = {s.span_id: s for s in spans}
    trace_ids = {s.trace_id for s in spans}
    assert len(trace_ids) == 1  # one trace from client context to kernels
    task_spans = [s for s in spans if s.kind == "executor"]
    op_spans = [s for s in spans if s.kind == "operator"]
    assert task_spans and op_spans
    exec_phase = next(s for s in spans
                      if s.kind == "scheduler" and s.name == "execution")
    for t in task_spans:
        assert t.parent_id == exec_phase.span_id
        assert t.attrs["executor_id"] == "obs-exec"
    for o in op_spans:
        # operator spans nest (ShuffleWriterExec -> HashAggregateExec ->
        # scan); every chain must climb to its task span
        cur, hops = o, 0
        while cur.kind == "operator" and hops < 50:
            cur = by_id[cur.parent_id]
            hops += 1
        assert cur.kind == "executor"
        assert o.end_ms >= o.start_ms


def test_span_propagation_standalone_path(tmp_path):
    """Same trace spine through the in-proc standalone cluster, with the
    pluggable in-memory collector receiving the export."""
    import pandas as pd

    from arrow_ballista_tpu.utils.config import (
        OBS_COLLECTOR,
        OBS_PROFILE_RETENTION,
    )

    ctx = BallistaContext.standalone(
        config=BallistaConfig({OBS_COLLECTOR: "memory",
                               OBS_PROFILE_RETENTION: 8}))
    try:
        ctx.register_table("t", pd.DataFrame({
            "g": np.arange(200) % 5, "v": np.arange(200)}))
        out = ctx.sql("select g, count(*) c from t group by g").to_pandas()
        assert len(out) == 5
        sched = ctx._standalone.scheduler
        job_id = sched.jobs.job_ids()[-1]
        prof = sched.obs.get_profile(job_id, sched.jobs.get_graph(job_id),
                                     sched.jobs.get_status(job_id))
        assert prof["state"] == "successful"
        assert any(t["operators"] for s in prof["stages"]
                   for t in s["tasks"])
        # the configured collector got the export (pluggability satellite)
        exported = sched.obs.collector.snapshot(prof["trace_id"])
        assert any(s.kind == "operator" for s in exported)
        assert sched.obs.profiles.capacity == 8
    finally:
        ctx.shutdown()


def test_trace_event_json_schema_unit():
    """spans_to_chrome on a synthetic tree: JSON-serializable, metadata
    events name processes/threads, nesting preserved via args."""
    from arrow_ballista_tpu.obs.tracing import Span, new_trace_id
    from arrow_ballista_tpu.obs.trace_event import spans_to_chrome

    tid = new_trace_id()
    root = Span("job j1", tid, attrs={"actor": "scheduler", "lane": "job j1"})
    child = Span("task j1/1/0", tid, parent_id=root.span_id,
                 kind="executor",
                 attrs={"actor": "executor e1", "lane": "stage 1 / p0"})
    child.end()
    root.end()
    doc = spans_to_chrome([root, child])
    encoded = json.loads(json.dumps(doc))
    assert encoded["traceId"] == tid
    xs = [e for e in encoded["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"job j1", "task j1/1/0"}
    assert all(e["dur"] >= 1.0 for e in xs)
    pids = {e["pid"] for e in xs}
    assert len(pids) == 2  # scheduler + executor processes
    child_ev = next(e for e in xs if e["name"] == "task j1/1/0")
    root_ev = next(e for e in xs if e["name"] == "job j1")
    assert child_ev["args"]["parent_id"] == root_ev["args"]["span_id"]


def test_admission_queue_depth_max_gauge():
    """Satellite fix: the high-water mark tracked by
    set_admission_queue_depth is actually exported by gather()."""
    from arrow_ballista_tpu.scheduler.metrics import InMemoryMetricsCollector

    c = InMemoryMetricsCollector()
    c.set_admission_queue_depth(3)
    c.set_admission_queue_depth(1)
    text = c.gather()
    assert "# TYPE admission_queue_depth_max gauge" in text
    lines = dict(l.rsplit(" ", 1) for l in text.splitlines()
                 if l and not l.startswith("#"))
    assert lines["admission_queue_depth"] == "1"
    assert lines["admission_queue_depth_max"] == "3"


def test_metrics_docs_consistency():
    """CI satellite: every emitted metric name appears in metrics.md."""
    import subprocess
    import sys
    from pathlib import Path

    script = Path(__file__).resolve().parent.parent / "tools" / \
        "check_metrics_docs.py"
    r = subprocess.run([sys.executable, str(script)], capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_rotating_file_logging(tmp_path):
    """Daemon log-to-file with rotation (reference config.rs:290-310
    LogRotationPolicy + tracing-appender rolling files)."""
    import logging

    from arrow_ballista_tpu.utils.logsetup import init_logging

    root = logging.getLogger()
    saved = list(root.handlers)
    saved_level = root.level
    try:
        init_logging("INFO", str(tmp_path), "sched", "minutely")
        logging.getLogger("t").info("hello rotation")
        for h in logging.getLogger().handlers:
            h.flush()
        path = tmp_path / "sched.log"
        assert path.exists() and "hello rotation" in path.read_text()
        import pytest

        with pytest.raises(ValueError):
            init_logging("INFO", str(tmp_path), "x", "weekly")
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
            h.close()
        for h in saved:
            root.addHandler(h)
        root.setLevel(saved_level)
