"""Observability: REST API, prometheus metrics, dot export."""
import json
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    sched = SchedulerNetService("127.0.0.1", 0, rest_port=0)
    sched.start()
    ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                        work_dir=str(tmp_path_factory.mktemp("obs")),
                        executor_id="obs-exec")
    ex.start()
    ctx = BallistaContext.remote("127.0.0.1", sched.port)
    ctx.register_table("t", pa.table({
        "g": pa.array(np.arange(1000) % 7, type=pa.int64()),
        "v": pa.array(np.arange(1000), type=pa.int64()),
    }))
    yield sched, ex, ctx
    ex.stop(notify=False)
    sched.stop()


def _get(sched, path, as_json=True):
    url = f"http://127.0.0.1:{sched.rest.port}{path}"
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read().decode()
    return json.loads(body) if as_json else body


def test_rest_state_and_executors(stack):
    sched, ex, ctx = stack
    state = _get(sched, "/api/state")
    assert state["executors"] == 1 and state["alive_executors"] == 1
    executors = _get(sched, "/api/executors")
    assert executors[0]["executor_id"] == "obs-exec"
    assert executors[0]["status"] == "active"


def test_rest_jobs_stages_dot_metrics(stack):
    sched, ex, ctx = stack
    out = ctx.sql("select g, sum(v) as s from t group by g order by g").to_pandas()
    assert len(out) == 7

    jobs = _get(sched, "/api/jobs")
    done = [j for j in jobs if j["state"] == "successful"]
    assert done, jobs
    job_id = done[0]["job_id"]
    assert done[0]["tasks_completed"] == done[0]["tasks_total"] > 0

    stages = _get(sched, f"/api/job/{job_id}/stages")
    assert len(stages) >= 2
    assert all(s["state"] == "successful" for s in stages)
    assert "ShuffleWriterExec" in stages[0]["plan"]

    dot = _get(sched, f"/api/job/{job_id}/dot", as_json=False)
    assert dot.startswith("digraph") and "shuffle" in dot

    metrics = _get(sched, "/api/metrics", as_json=False)
    assert "job_submitted_total" in metrics
    assert "job_exec_time_seconds_count" in metrics
    submitted = [l for l in metrics.splitlines()
                 if l.startswith("job_submitted_total")][0]
    assert int(submitted.split()[-1]) >= 1


def test_rest_cancel_patch(stack):
    sched, ex, ctx = stack
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{sched.rest.port}/api/job/nonexistent",
        method="PATCH")
    with urllib.request.urlopen(req, timeout=10) as r:
        body = json.loads(r.read().decode())
    assert body["cancelled"] == "nonexistent"


def test_web_ui_served(stack):
    """The dashboard page is served at / and references the API it polls
    (reference: React UI over the same /api endpoints, ui/src/*)."""
    sched, ex, ctx = stack
    html = _get(sched, "/", as_json=False)
    assert "<!doctype html>" in html.lower()
    for marker in ("/api/state", "/api/executors", "/api/jobs",
                   "Ballista-TPU Scheduler"):
        assert marker in html
    assert _get(sched, "/ui", as_json=False) == html


def test_keda_scaler_endpoint(stack):
    """KEDA external-scaler shape (reference external_scaler.rs:14-60)."""
    sched, ex, ctx = stack
    out = _get(sched, "/api/scaler")
    assert "inflight_tasks" in out and isinstance(out["inflight_tasks"], int)


def test_rotating_file_logging(tmp_path):
    """Daemon log-to-file with rotation (reference config.rs:290-310
    LogRotationPolicy + tracing-appender rolling files)."""
    import logging

    from arrow_ballista_tpu.utils.logsetup import init_logging

    root = logging.getLogger()
    saved = list(root.handlers)
    saved_level = root.level
    try:
        init_logging("INFO", str(tmp_path), "sched", "minutely")
        logging.getLogger("t").info("hello rotation")
        for h in logging.getLogger().handlers:
            h.flush()
        path = tmp_path / "sched.log"
        assert path.exists() and "hello rotation" in path.read_text()
        import pytest

        with pytest.raises(ValueError):
            init_logging("INFO", str(tmp_path), "x", "weekly")
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
            h.close()
        for h in saved:
            root.addHandler(h)
        root.setLevel(saved_level)
