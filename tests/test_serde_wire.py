"""Exhaustive wire-type serde round-trips.

Reflects over ``serde.WIRE_TYPES`` so the NEXT control-plane dataclass that
gets registered is automatically exercised — and an unregistered one fails
the companion lint (serde-completeness) plus the sample-coverage assertion
here.  The universal property is canonical round-trip stability,
``to(from(to(x))) == to(x)``, which holds even for types whose fields
(plan objects, span objects) lack structural ``__eq__``; every encoding
must also survive ``json.dumps`` (the wire framing is JSON).
"""
import json

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu import serde
from arrow_ballista_tpu.models import expr as E
from arrow_ballista_tpu.models.schema import INT64, Field, Schema
from arrow_ballista_tpu.obs.journal import JournalEvent
from arrow_ballista_tpu.obs.tracing import Span
from arrow_ballista_tpu.ops.physical import MemoryScanExec, Partitioning
from arrow_ballista_tpu.ops.shuffle import (
    PartitionLocation,
    ShuffleWriterExec,
    ShuffleWritePartition,
)
from arrow_ballista_tpu.scheduler.types import (
    EXECUTION_ERROR,
    FETCH_PARTITION_ERROR,
    ExecutorHeartbeat,
    ExecutorMetadata,
    ExecutorReservation,
    FailedReason,
    JobLease,
    JobStatus,
    TaskDescription,
    TaskId,
    TaskStatus,
)

SCHEMA = Schema([Field("k", INT64), Field("v", INT64)])


def _plan():
    table = pa.table({"k": pa.array(np.arange(8, dtype=np.int64)),
                      "v": pa.array(np.arange(8, dtype=np.int64))})
    return ShuffleWriterExec(MemoryScanExec(SCHEMA, table, partitions=2),
                             Partitioning.hash([E.Column("k")], 4),
                             stage_id=3)


LOCATION = PartitionLocation("exec-1", 2, 5, "/tmp/shuffle/data-5.arrow",
                             num_rows=100, num_bytes=4096,
                             host="10.0.0.2", port=50051)

# representative payloads per registered wire type: defaults-only AND
# fully-populated variants, plus the tricky shapes (nested Optional
# metadata, int-keyed location maps, span-bearing statuses)
SAMPLES = {
    TaskId: [
        TaskId("job-1", 2, 7),
        TaskId("job-1", 2, 7, task_attempt=3, stage_attempt=1),
        TaskId("job-1", 2, 7, task_attempt=4, stage_attempt=1,
               speculative=True),
    ],
    TaskDescription: [
        TaskDescription(TaskId("job-1", 3, 0), _plan()),
        TaskDescription(TaskId("job-1", 3, 1), _plan(), task_internal_id=42,
                        scalars={"sq0": 12.5},
                        trace={"trace_id": "t" * 32, "span_id": "s" * 16}),
    ],
    TaskStatus: [
        TaskStatus(TaskId("job-1", 1, 0), "exec-1", "success"),
        TaskStatus(TaskId("job-1", 1, 1), "exec-2", "failed",
                   shuffle_writes=[ShuffleWritePartition(0, "/tmp/d0", 5, 64)],
                   failure=FailedReason(FETCH_PARTITION_ERROR, "gone",
                                        map_stage_id=1, map_partition_id=4,
                                        executor_id="exec-3"),
                   launch_time_ms=1, start_time_ms=2, end_time_ms=3,
                   metrics={"0:ScanExec": {"output_rows": 8}},
                   process_id="pid-1",
                   spans=[Span("task", trace_id="t" * 32, span_id="s" * 16,
                               kind="executor", start_ms=1.0, end_ms=2.0)]),
        TaskStatus(TaskId("job-1", 1, 2), "exec-1", "success",
                   device_stats={"jit_compiles": 4, "jit_retraces": 1,
                                 "jit_compile_time": 0.82,
                                 "h2d_bytes": 17408, "d2h_bytes": 16392,
                                 "device_mem_peak": 262144,
                                 "host_mem_peak": 104857600}),
    ],
    FailedReason: [
        FailedReason(EXECUTION_ERROR, "boom"),
        FailedReason(FETCH_PARTITION_ERROR, "lost", map_stage_id=2,
                     map_partition_id=9, executor_id="exec-9"),
    ],
    ShuffleWritePartition: [
        ShuffleWritePartition(3, "/tmp/shuffle/data-3.arrow", 128, 8192),
        ShuffleWritePartition(4, "/tmp/shuffle/data-4.arrow", 128, 8192,
                              checksum=0xDEADBEEF),
    ],
    PartitionLocation: [
        PartitionLocation("exec-1", 0, 1, "/tmp/p"),
        PartitionLocation("exec-1", 0, 2, "/tmp/p2", checksum=0xCAFEF00D),
        PartitionLocation("exec-1", 1, 3, "/tmp/p3", num_rows=9,
                          num_bytes=512, host="10.0.0.3", port=50051,
                          checksum=0x1234, grpc_port=50052,
                          format="arrow_file"),
        LOCATION,
    ],
    ExecutorMetadata: [
        ExecutorMetadata("exec-1"),
        ExecutorMetadata("exec-2", host="10.0.0.9", port=7000,
                         grpc_port=7001, task_slots=8),
    ],
    ExecutorHeartbeat: [
        ExecutorHeartbeat("exec-1", timestamp=123.5),
        ExecutorHeartbeat("exec-2", timestamp=124.0, status="terminating",
                          metadata=ExecutorMetadata("exec-2", port=7000)),
        ExecutorHeartbeat("exec-3", timestamp=125.0, memory_pressure=0.7),
    ],
    ExecutorReservation: [
        ExecutorReservation("exec-1"),
        ExecutorReservation("exec-2", job_id="job-9"),
    ],
    JobStatus: [
        JobStatus("job-1", "running"),
        JobStatus("job-2", "failed", error="shed", retriable=True),
        JobStatus("job-3", "successful",
                  locations={0: [LOCATION], 3: [LOCATION, LOCATION]}),
    ],
    JobLease: [
        JobLease("job-1"),
        JobLease("job-2", owner="scheduler-a1b2", epoch=7, ts=1700000000.25,
                 endpoint="10.0.0.7:50050"),
    ],
    JournalEvent: [
        JournalEvent(seq=1, ts_ms=1700000000123, kind="job.submitted"),
        JournalEvent(seq=9, ts_ms=1700000000456, kind="task.finish",
                     actor="scheduler-a1b2", job_id="job-1", epoch=3,
                     parent=4, attrs={"stage_id": 2, "partition": 0,
                                      "attempt": 1, "state": "success",
                                      "executor_id": "exec-1"}),
    ],
}


def test_every_wire_type_has_samples():
    missing = [t.__name__ for t in serde.WIRE_TYPES if t not in SAMPLES]
    assert not missing, (
        f"wire types without representative payloads: {missing} — add "
        f"SAMPLES entries so new registrations are actually exercised")
    stale = [t.__name__ for t in SAMPLES if t not in serde.WIRE_TYPES]
    assert not stale, f"SAMPLES covers unregistered types: {stale}"


@pytest.mark.parametrize("wire_type", sorted(serde.WIRE_TYPES,
                                             key=lambda t: t.__name__),
                         ids=lambda t: t.__name__)
def test_round_trip_stability_and_json_safety(wire_type):
    to_obj, from_obj = serde.WIRE_TYPES[wire_type]
    for sample in SAMPLES.get(wire_type, []):
        encoded = to_obj(sample)
        # the wire framing is JSON: every encoding must survive it verbatim
        rehydrated = json.loads(json.dumps(encoded))
        decoded = from_obj(rehydrated)
        assert isinstance(decoded, wire_type)
        assert to_obj(decoded) == encoded, (
            f"{wire_type.__name__} round-trip is not stable")


def test_decoded_fields_match_for_value_types():
    """Types whose fields are all plain values must decode EQUAL, not just
    stably — catches a to/from pair that consistently drops a field."""
    for wire_type in (TaskId, FailedReason, ShuffleWritePartition,
                      PartitionLocation, ExecutorMetadata,
                      ExecutorReservation, JobLease):
        to_obj, from_obj = serde.WIRE_TYPES[wire_type]
        for sample in SAMPLES[wire_type]:
            assert from_obj(json.loads(json.dumps(to_obj(sample)))) == sample


def test_job_status_locations_rekeyed_to_int():
    to_obj, from_obj = serde.WIRE_TYPES[JobStatus]
    decoded = from_obj(json.loads(json.dumps(to_obj(SAMPLES[JobStatus][2]))))
    assert set(decoded.locations) == {0, 3}
    assert all(isinstance(k, int) for k in decoded.locations)
    assert decoded.locations[3][1] == LOCATION


def test_heartbeat_nested_metadata_round_trips():
    to_obj, from_obj = serde.WIRE_TYPES[ExecutorHeartbeat]
    hb = SAMPLES[ExecutorHeartbeat][1]
    decoded = from_obj(json.loads(json.dumps(to_obj(hb))))
    assert decoded.metadata == hb.metadata
    assert from_obj(to_obj(SAMPLES[ExecutorHeartbeat][0])).metadata is None


def test_heartbeat_memory_pressure_omitted_when_zero():
    """Pressure 0.0 (the unbudgeted default) must stay off the wire so
    idle fleets and old-wire peers pay nothing; a nonzero value round
    trips exactly."""
    to_obj, from_obj = serde.WIRE_TYPES[ExecutorHeartbeat]
    calm = to_obj(SAMPLES[ExecutorHeartbeat][0])
    assert "memory_pressure" not in calm
    assert from_obj(calm).memory_pressure == 0.0
    hot = to_obj(SAMPLES[ExecutorHeartbeat][2])
    assert hot["memory_pressure"] == pytest.approx(0.7)
    assert from_obj(json.loads(json.dumps(hot))).memory_pressure == \
        pytest.approx(0.7)


def test_scalarref_carries_dtype_for_planless_substitution():
    """A deserialized scalar ref has no plan (only the id crosses the
    wire) — the result dtype must ride along so remote executors can
    re-scale decimal scaled-int values without dereferencing the plan."""
    from arrow_ballista_tpu.models.schema import DataType
    from arrow_ballista_tpu.ops.operators import _substitute_scalars

    dec = Schema([Field("s", DataType("decimal", 2))])

    class _Plan:  # serialization only reads plan.schema
        schema = dec

    plan = E.ScalarSubquery(_Plan())
    object.__setattr__(plan, "scalar_id", "sq7")

    obj = json.loads(json.dumps(serde.expr_to_obj(plan)))
    assert obj["dt"] == {"kind": "decimal", "scale": 2}

    decoded = serde.expr_from_obj(obj)
    assert decoded.plan is None
    assert decoded.scalar_dtype == DataType("decimal", 2)
    # re-serialization of a deserialized ref keeps the dtype (executors
    # re-serde plans on some paths)
    assert serde.expr_to_obj(decoded)["dt"] == obj["dt"]

    # value arrives as a raw scaled int; substitution must rescale it
    # using the attached dtype, not the (absent) plan schema
    lit = _substitute_scalars(decoded, {"sq7": 12345})
    assert isinstance(lit, E.Lit)
    assert lit.value == 123.45


def test_device_stats_key_absent_when_empty():
    """Observatory-off statuses must be byte-identical to the pre-device
    wire format: the device_stats key only appears when non-empty."""
    bare = TaskStatus(TaskId("job-1", 4, 0), "exec-1", "success")
    obj = serde.status_to_obj(bare)
    assert "device_stats" not in obj
    assert serde.status_from_obj(obj).device_stats == {}
    carrying = TaskStatus(TaskId("job-1", 4, 1), "exec-1", "success",
                          device_stats={"h2d_bytes": 1024})
    assert serde.status_to_obj(carrying)["device_stats"] == \
        {"h2d_bytes": 1024}


def test_journal_key_absent_when_empty():
    """Flight-recorder-off statuses and checkpoints must be byte-identical
    to the pre-journal wire format: the journal key only appears when
    events actually ride along (same contract as device_stats)."""
    bare = TaskStatus(TaskId("job-1", 4, 0), "exec-1", "success")
    obj = serde.status_to_obj(bare)
    assert "journal" not in obj
    assert serde.status_from_obj(obj).journal == []
    events = [{"seq": 3, "ts_ms": 1700000000789, "kind": "task.run",
               "actor": "exec-1", "job_id": "job-1",
               "attrs": {"stage_id": 4, "partition": 0}}]
    carrying = TaskStatus(TaskId("job-1", 4, 1), "exec-1", "success",
                          journal=[dict(e) for e in events])
    wired = json.loads(json.dumps(serde.status_to_obj(carrying)))
    assert serde.status_from_obj(wired).journal == events
