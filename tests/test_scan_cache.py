"""Device-resident scan cache: HBM as the buffer pool (utils/table_cache.py).

The reference relies on ParquetExec + OS page cache for repeated scans; the
TPU-native analog keeps converted device batches resident across queries so
warm queries skip read+convert+H2D entirely.
"""
import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from arrow_ballista_tpu.catalog import ParquetTable
from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.models import expr as E
from arrow_ballista_tpu.ops.physical import TaskContext
from arrow_ballista_tpu.utils import table_cache
from arrow_ballista_tpu.utils.config import BallistaConfig, SCAN_CACHE_BYTES


@pytest.fixture
def parquet_file(tmp_path):
    path = str(tmp_path / "t.parquet")
    n = 4000
    t = pa.table({
        "x": pa.array(np.arange(n, dtype=np.int64)),
        "s": pa.array(np.where(np.arange(n) % 3 == 0, "a", "b")),
    })
    pq.write_table(t, path, row_group_size=1000)
    return path


@pytest.fixture(autouse=True)
def fresh_cache():
    table_cache.CACHE.clear()
    yield
    table_cache.CACHE.clear()


def _scan(path, filters=()):
    return ParquetTable("t", path).scan(None, list(filters), 2)


def test_second_scan_hits(parquet_file):
    scan = _scan(parquet_file)
    ctx = TaskContext()
    first = scan.execute(0, ctx)
    assert scan.metrics().to_dict().get("scan_cache_hits", 0) == 0
    second = scan.execute(0, ctx)
    assert scan.metrics().to_dict().get("scan_cache_hits", 0) == 1
    for a, b in zip(first, second):
        np.testing.assert_array_equal(np.asarray(a.columns["x"]),
                                      np.asarray(b.columns["x"]))
    # a DIFFERENT scan instance over the same file + projection also hits
    other = _scan(parquet_file)
    other.execute(0, TaskContext())
    assert other.metrics().to_dict().get("scan_cache_hits", 0) == 1


def test_filters_apply_on_top_of_cached_batches(parquet_file):
    ctx = TaskContext()
    _scan(parquet_file).execute(0, ctx)  # warm, unfiltered
    filt = _scan(parquet_file, [E.BinOp("<", E.Column("x"), E.Lit(10))])
    batches = [b for b in (filt.execute(p, ctx)
                           for p in range(filt.output_partition_count()))]
    total = sum(b.num_rows for part in batches for b in part)
    assert total == 10
    # and the cached entry still serves unfiltered rows
    plain = _scan(parquet_file)
    rows = sum(b.num_rows for p in range(plain.output_partition_count())
               for b in plain.execute(p, ctx))
    assert rows == 4000


def test_file_rewrite_invalidates(parquet_file):
    ctx = TaskContext()
    _scan(parquet_file).execute(0, ctx)
    stats0 = table_cache.CACHE.stats()
    assert stats0["entries"] >= 1
    time.sleep(0.01)
    n = 4000
    t = pa.table({
        "x": pa.array(np.arange(n, dtype=np.int64) + 1),
        "s": pa.array(["z"] * n),
    })
    pq.write_table(t, parquet_file, row_group_size=1000)
    os.utime(parquet_file)  # belt and braces: force a new mtime
    fresh = _scan(parquet_file)
    out = fresh.execute(0, ctx)
    assert fresh.metrics().to_dict().get("scan_cache_hits", 0) == 0
    assert int(np.asarray(out[0].columns["x"])[0]) >= 1


def test_budget_eviction_lru(parquet_file):
    ctx = TaskContext()
    scan = _scan(parquet_file)
    scan.execute(0, ctx)
    stats = table_cache.CACHE.stats()
    entry_bytes = stats["bytes"]
    assert entry_bytes > 0
    # budget below one entry: the put is refused / evicted
    table_cache.CACHE.set_budget(entry_bytes - 1)
    assert table_cache.CACHE.stats()["entries"] == 0
    cfg = BallistaConfig({SCAN_CACHE_BYTES: str(entry_bytes - 1)})
    scan2 = _scan(parquet_file)
    scan2.execute(0, TaskContext(config=cfg))
    assert table_cache.CACHE.stats()["entries"] == 0


def test_disabled_by_config(parquet_file):
    cfg = BallistaConfig({SCAN_CACHE_BYTES: "0"})
    ctx = TaskContext(config=cfg)
    scan = _scan(parquet_file)
    scan.execute(0, ctx)
    scan.execute(0, ctx)
    assert scan.metrics().to_dict().get("scan_cache_hits", 0) == 0
    assert table_cache.CACHE.stats()["entries"] == 0


def test_end_to_end_warm_query_correct(parquet_file):
    ctx = BallistaContext.local()
    ctx.register_parquet("t", parquet_file)
    q = "select s, count(*) as n, sum(x) as sx from t group by s order by s"
    cold = ctx.sql(q).to_pandas()
    warm = ctx.sql(q).to_pandas()
    assert cold.equals(warm)
    assert table_cache.CACHE.stats()["hits"] >= 1


def test_auto_budget_is_keyed_on_backend_platform():
    """'auto' resolves per backend: accelerators get the HBM-sized pool,
    CPU backends the small one (tests run with JAX_PLATFORMS=cpu, where
    'device' arrays are host RAM pinned per daemon process)."""
    assert table_cache.resolve_budget("auto") == table_cache.DEFAULT_BUDGET_CPU
    assert table_cache.DEFAULT_BUDGET_CPU < table_cache.DEFAULT_BUDGET
    # explicit sizes still pass through untouched
    assert table_cache.resolve_budget("123") == 123
    assert table_cache.resolve_budget(0) == 0
