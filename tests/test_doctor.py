"""Flight recorder & query doctor: causal journal, forensics, diagnosis.

Four layers, matching how the PR is built:

  1. journal mechanics: causal chaining (launch -> finish parents),
     enabled/disabled cost contract (no wire bytes, no per-event
     allocation on the hot task-status path), counters, ring bounds;
  2. clean-run e2e: a standalone query produces a valid forensics bundle
     with the full lifecycle timeline and ZERO doctor findings;
  3. seeded pathologies, each yielding exactly the expected diagnosis:
     a straggler (``executor.task.slow`` failpoint + speculation win), a
     skewed synthetic join (hash-partition row skew), alias-churn
     retraces (static-key churn through the shared pack wrapper, folded
     into the serving stage the way a long-lived process accumulates
     it), plus bundle-level fixtures for shuffle-hotspot,
     cache-miss-churn and control-plane-churn;
  4. fleet failover (chaos): a shard killed mid-job leaves one forensics
     bundle whose timeline spans pre- and post-adoption under one job
     id, with the fencing epoch marked on post-adoption events.
"""
import copy
import json
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu import faults, serde
from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.obs import device as dev
from arrow_ballista_tpu.obs import journal
from arrow_ballista_tpu.obs.doctor import (
    CACHE_MISS_MIN,
    HOTSPOT_IMBALANCE_MIN,
    RETRACE_STORM_MIN,
    SKEW_COEFFICIENT_MIN,
    assemble_forensics,
    diagnose,
    render_diagnosis,
    validate_bundle,
)
from arrow_ballista_tpu.utils.config import BallistaConfig
from arrow_ballista_tpu.utils.errors import PlanningError


@pytest.fixture(autouse=True)
def _journal_on():
    """Fresh, enabled journal per test; components never force-disable an
    explicitly enabled journal (enable-only switch), so this survives
    standalone cluster construction."""
    journal.reset()
    journal.set_enabled(True)
    faults.clear()
    yield
    faults.clear()
    journal.reset()
    journal.set_enabled(False)


def _table(rng, n, groups=7):
    return pa.table({
        "g": pa.array(rng.integers(0, groups, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    })


def _standalone(conf=None, concurrent_tasks=2, num_executors=2):
    base = {"ballista.shuffle.partitions": "4"}
    base.update(conf or {})
    return BallistaContext.standalone(BallistaConfig(base),
                                      concurrent_tasks=concurrent_tasks,
                                      num_executors=num_executors)


def _rules(diag):
    return [f["rule"] for f in diag["findings"]]


# --------------------------------------------------------------------------
# journal mechanics
# --------------------------------------------------------------------------

def test_emit_chains_lifecycle_and_causal_keys():
    journal.emit_job("job.submitted", "j1")
    journal.emit_job("job.admitted", "j1")
    journal.emit("task.launch", job_id="j1",
                 causal_key=("task", "j1", 1, 0, 0), stage_id=1, partition=0)
    journal.emit("task.finish", job_id="j1",
                 parent_key=("task", "j1", 1, 0, 0), state="success")
    tl = journal.job_timeline("j1")
    assert [e["kind"] for e in tl] == \
        ["job.submitted", "job.admitted", "task.launch", "task.finish"]
    submitted, admitted, launch, finish = tl
    assert admitted["parent"] == submitted["seq"], \
        "lifecycle events must chain causally"
    assert finish["parent"] == launch["seq"], \
        "a finish must point at its launch via the causal-key registry"
    assert launch["attrs"] == {"stage_id": 1, "partition": 0}


def test_epoch_stamping_and_clear():
    journal.set_job_epoch("j1", 3)
    journal.emit_job("lease.adopt", "j1")
    journal.set_job_epoch("j1", 0)
    journal.emit_job("job.successful", "j1")
    adopt, done = journal.job_timeline("j1")
    assert adopt["epoch"] == 3
    assert "epoch" not in done, "epoch 0 must clear the stamp"


def test_absorb_dedups_in_process_executor_events():
    """Standalone executors share the process journal: their task events
    land in the timeline at emit time, so the TaskStatus piggyback copy
    must not double them — while a remote executor's events (different
    actor) always merge."""
    journal.set_actor("local")
    with journal.task_scope() as buf:
        journal.emit("task.run", job_id="j1", stage_id=1, partition=0)
    assert len(buf) == 1
    assert journal.absorb("j1", buf) == 0, "piggyback of own events dedups"
    remote = [{"seq": 1, "ts_ms": 1, "kind": "task.run", "actor": "exec-r",
               "job_id": "j1", "attrs": {"stage_id": 1, "partition": 1}}]
    assert journal.absorb("j1", remote) == 1
    kinds = [(e.get("actor"), e["kind"]) for e in journal.job_timeline("j1")]
    assert kinds == [("local", "task.run"), ("exec-r", "task.run")]


def test_disabled_journal_allocates_nothing_and_is_wire_silent():
    """The regression contract for the hot task-status path: journal off
    => emit returns None without buffering, task_scope yields None (the
    shared null scope, no per-task object), counters stay zero, and a
    TaskStatus encodes byte-identically to the pre-journal wire format."""
    journal.set_enabled(False)
    assert journal.emit("task.run", job_id="j1", stage_id=1) is None
    scope = journal.task_scope()
    assert scope is journal.task_scope(), \
        "disabled task_scope must reuse ONE shared null object"
    with scope as buf:
        assert buf is None
        journal.emit("task.run", job_id="j1", stage_id=1)
    assert journal.job_timeline("j1") == []
    assert journal.counters() == (0, 0)

    from arrow_ballista_tpu.scheduler.types import TaskId, TaskStatus
    st = TaskStatus(TaskId("j1", 1, 0), "exec-1", "success")
    wire = json.dumps(serde.status_to_obj(st), sort_keys=True)
    assert "journal" not in wire, \
        "disabled journal must add zero bytes to task statuses"


def test_ring_bounds_and_dropped_counter():
    journal.configure(capacity=8)
    try:
        for i in range(12):
            journal.emit("tick", job_id="j1", i=i)
        emitted, dropped = journal.counters()
        assert emitted == 12 and dropped == 8, \
            "overflow past capacity must count drops (ring + job timeline)"
        tl = journal.job_timeline("j1")
        assert len(tl) == 8 and tl[-1]["attrs"]["i"] == 11, \
            "the ring keeps the newest events"
    finally:
        journal.configure(capacity=4096)


def test_spill_writes_jsonl(tmp_path):
    spill = tmp_path / "journal.jsonl"
    journal.configure(spill_path=str(spill))
    try:
        journal.emit_job("job.submitted", "j1")
        journal.emit_job("job.successful", "j1")
        lines = [json.loads(l) for l in
                 spill.read_text().strip().splitlines()]
        assert [l["kind"] for l in lines] == \
            ["job.submitted", "job.successful"]
    finally:
        journal.configure(spill_path="")


# --------------------------------------------------------------------------
# clean run: valid bundle, full timeline, zero findings
# --------------------------------------------------------------------------

def test_clean_run_bundle_timeline_and_zero_findings():
    ctx = _standalone()
    try:
        ctx.register_table("t", _table(np.random.default_rng(3), 4000))
        df = ctx.sql("select g, sum(v) as s, count(*) as n from t "
                     "group by g order by g").to_pandas()
        assert len(df) == 7

        bundle = ctx.forensics()
        assert validate_bundle(bundle) == []
        assert bundle["journal_enabled"]
        tl = bundle["journal"]
        kinds = [e["kind"] for e in tl]
        for k in ("job.submitted", "job.admitted", "job.planned",
                  "stage.resolved", "task.launch", "task.run",
                  "task.finish", "job.successful"):
            assert k in kinds, f"clean-run timeline must record {k}: {kinds}"
        assert kinds[0] == "job.submitted"
        assert kinds[-1] == "job.successful"
        # every finish chains to the launch that minted the attempt
        launches = {e["seq"]: e for e in tl if e["kind"] == "task.launch"}
        finishes = [e for e in tl if e["kind"] == "task.finish"]
        assert finishes and all(e.get("parent") in launches for e in finishes)
        for e in finishes:
            la = launches[e["parent"]]["attrs"]
            assert (la["stage_id"], la["partition"]) == \
                (e["attrs"]["stage_id"], e["attrs"]["partition"])
        # executor-side task.run events carry through the status piggyback
        runs = [e for e in tl if e["kind"] == "task.run"]
        assert len(runs) == len(finishes)

        diag = ctx.doctor()
        assert diag["findings"] == [], \
            f"clean run must produce zero findings: {diag['text']}"
        assert len(diag["rules_evaluated"]) >= 6
        assert "no pathology detected" in diag["text"]
        json.dumps(bundle)  # the artifact is one self-contained JSON doc
    finally:
        ctx.shutdown()


def test_forensics_rest_and_cli_surfaces():
    from arrow_ballista_tpu.scheduler.rest import RestApi

    ctx = _standalone()
    api = None
    try:
        ctx.register_table("t", _table(np.random.default_rng(4), 4000))
        ctx.sql("select g, sum(v) as s from t group by g").to_pandas()
        job_id = ctx._standalone.last_job_id

        api = RestApi(ctx._standalone.scheduler)
        api.start()

        def get(path, as_json=True):
            url = f"http://127.0.0.1:{api.port}{path}"
            with urllib.request.urlopen(url, timeout=10) as r:
                body = r.read().decode()
            return json.loads(body) if as_json else body

        bundle = get(f"/api/job/{job_id}/forensics")
        assert validate_bundle(bundle) == []
        assert bundle["job_id"] == job_id

        diag = get(f"/api/job/{job_id}/doctor")
        assert diag["job_id"] == job_id and diag["findings"] == []
        assert render_diagnosis(diag) == diag["text"]

        with pytest.raises(urllib.error.HTTPError) as e:
            get("/api/job/zzz-nope/forensics")
        assert e.value.code == 404

        # fleet-aware history: standalone has no registry -> local shard
        hist = get("/api/cluster/history")
        assert [s["local"] for s in hist["shards"]] == [True]
        assert hist["shards"][0]["scheduler_id"]
        assert "pending_tasks" in hist["shards"][0]

        # /api/metrics syncs journal counters into the exposition
        text = get("/api/metrics", as_json=False)
        assert "journal_events_total" in text
        assert "journal_events_dropped_total 0" in text
        emitted = journal.counters()[0]
        assert f"journal_events_total {emitted}" in text

        # CLI \doctor prints the rendered diagnosis for the last job
        from arrow_ballista_tpu.cli import run_command
        run_command(ctx, "\\doctor", False)
        run_command(ctx, f"\\doctor {job_id}", False)
    finally:
        if api is not None:
            api.stop()
        ctx.shutdown()


def test_forensics_unknown_job_raises():
    ctx = _standalone(num_executors=1)
    try:
        with pytest.raises(PlanningError):
            ctx.forensics("job-that-never-was")
        with pytest.raises(PlanningError):
            ctx.forensics()  # nothing ran yet
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# seeded pathologies -> exactly the expected diagnosis
# --------------------------------------------------------------------------

def test_straggler_failpoint_diagnosed():
    """One stage-1 task stalls 2 s (``executor.task.slow``); speculation
    duplicates it and the copy wins.  The doctor must diagnose exactly a
    straggler on stage 1, citing the speculation win."""
    ctx = _standalone({
        "ballista.speculation.enabled": "true",
        "ballista.speculation.quantile": "0.5",
        "ballista.speculation.multiplier": "1.2",
        "ballista.speculation.min_runtime.seconds": "0.3",
        "ballista.speculation.interval.seconds": "0.1",
    })
    try:
        ctx.register_table("t", _table(np.random.default_rng(23), 4000))
        sql = "select g, sum(v) as s, count(*) as n from t group by g order by g"
        plan = faults.FaultPlan.from_obj({"seed": 21, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 2000, "times": 1,
            "match": {"stage_id": 1, "executor_id": "executor-0"}}]})
        with faults.use_plan(plan):
            ctx.sql(sql).to_pandas()
        assert plan.events, "the slow failpoint must actually have fired"

        bundle = ctx.forensics()
        kinds = [e["kind"] for e in bundle["journal"]]
        assert "speculation.launch" in kinds
        assert "speculation.win" in kinds
        assert "fault.fired" in kinds, \
            "failpoint firings must land in the journal"

        diag = diagnose(bundle)
        assert _rules(diag) == ["straggler"], diag["text"]
        f = diag["findings"][0]
        assert f["stage_id"] == 1
        assert f["evidence"]["speculation_wins"] >= 1
        assert f["evidence"]["speculative_launches"] >= 1
        assert "speculation" in f["remedy"]
    finally:
        ctx.shutdown()


def test_partition_skew_join_diagnosed():
    """A join whose probe side hashes 90% of its rows to one shuffle
    partition.  The doctor must diagnose exactly a partition skew on the
    probe map stage, citing the skew coefficient and the hot partition."""
    ctx = _standalone({"ballista.join.broadcast_threshold": "0"})
    try:
        rng = np.random.default_rng(7)
        n = 24000
        k = np.where(rng.random(n) < 0.9, 0,
                     rng.integers(1, 16, n)).astype(np.int64)
        ctx.register_table("fact", pa.table({
            "k": pa.array(k),
            "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
        }))
        ctx.register_table("dim", pa.table({
            "k": pa.array(np.arange(16, dtype=np.int64)),
            "w": pa.array(rng.integers(0, 9, 16).astype(np.int64)),
        }))
        ctx.sql("select f.k, count(*) as c, sum(f.v) as s "
                "from fact f join dim d on f.k = d.k "
                "group by f.k order by f.k").to_pandas()

        diag = ctx.doctor()
        assert _rules(diag) == ["partition-skew"], diag["text"]
        f = diag["findings"][0]
        ev = f["evidence"]
        assert ev["skew_coefficient"] >= SKEW_COEFFICIENT_MIN
        assert ev["output_rows"] == n
        assert ev["hot_partition_rows"] > n // 2, \
            "the cited hot partition must carry the skewed key"
        assert "aqe" in f["remedy"]
        # the skewed stage is the fact-side map stage in the bundle
        st = next(s for s in ctx.forensics()["stages"]
                  if s["stage_id"] == f["stage_id"])
        assert st["skew"] == ev["skew_coefficient"]
    finally:
        ctx.shutdown()


def test_retrace_storm_alias_churn_diagnosed():
    """Alias churn re-keys the shared pack wrapper on every statement —
    genuine retraces measured by the device observatory.  A single toy
    job cannot accumulate a storm (shape bucketing exists precisely to
    prevent that), so the measured churn is folded into the serving
    stage of a real bundle the way a long-lived process accumulates it
    across stage re-runs; the diagnosis must be exactly a retrace storm
    citing the retrace/compile ratio."""
    ctx = _standalone()
    try:
        ctx.register_table("t", _table(np.random.default_rng(5), 4000))
        ctx.sql("select g, sum(v) as s from t group by g order by g"
                ).to_pandas()  # warm: plan-shape wrappers compile here
        before = dev.STATS.snapshot()
        for i in range(RETRACE_STORM_MIN + 2):
            ctx.sql(f"select g, sum(v) as churn_{i} from t "
                    "group by g order by g").to_pandas()
        after = dev.STATS.snapshot()
        retraces = int(after["jit_retraces"] - before["jit_retraces"])
        assert retraces >= RETRACE_STORM_MIN, \
            "every churned alias must re-trace the shared pack wrapper"

        bundle = ctx.forensics()
        st = bundle["stages"][0]
        st.setdefault("device", {})
        st["device"]["jit_retraces"] = retraces
        st["device"]["jit_compiles"] = 1
        # the churn loop also genuinely churns the plan cache (every alias
        # is a new statement) — neutralize that axis here; the dedicated
        # cache-miss test covers it e2e
        bundle["metrics"]["plan_cache_misses"] = 0
        diag = diagnose(bundle)
        assert _rules(diag) == ["retrace-storm"], diag["text"]
        f = diag["findings"][0]
        assert f["evidence"]["jit_retraces"] == retraces
        assert f["severity"] >= 3.0, "severity is the retrace/compile ratio"
        assert "batch" in f["remedy"] or "fuse" in f["remedy"]
    finally:
        ctx.shutdown()


def _clean_bundle_template():
    """A real, clean bundle to mutate for bundle-level rule fixtures."""
    ctx = _standalone(num_executors=1)
    try:
        ctx.register_table("t", _table(np.random.default_rng(6), 4000))
        ctx.sql("select g, sum(v) as s from t group by g").to_pandas()
        bundle = ctx.forensics()
    finally:
        ctx.shutdown()
    assert diagnose(bundle)["findings"] == []
    return bundle


def test_shuffle_hotspot_rule():
    bundle = _clean_bundle_template()
    st = bundle["stages"][0]
    # max/mean imbalance is bounded by the partition count, so a ≥4x
    # hotspot needs more than 4 partitions to be expressible at all
    hot = 6 << 20
    st["partition_bytes"] = {"0": hot,
                             **{str(p): 1 << 16 for p in range(1, 8)}}
    diag = diagnose(bundle)
    assert _rules(diag) == ["shuffle-hotspot"], diag["text"]
    f = diag["findings"][0]
    assert f["evidence"]["max_partition_bytes"] == hot
    assert f["evidence"]["bytes_imbalance"] >= HOTSPOT_IMBALANCE_MIN
    assert "ballista.shuffle.partitions" in f["remedy"]


def test_cache_miss_churn_diagnosed_e2e():
    """Every statement unique -> the plan cache misses on all of them;
    the scheduler's own counters carry the evidence into the bundle."""
    ctx = _standalone(num_executors=1)
    try:
        ctx.register_table("t", _table(np.random.default_rng(9), 4000))
        for i in range(CACHE_MISS_MIN + 4):
            ctx.sql(f"select g, sum(v) as s from t where v < {90 - i} "
                    "group by g").to_pandas()
        diag = ctx.doctor()
        assert _rules(diag) == ["cache-miss-churn"], diag["text"]
        ev = diag["findings"][0]["evidence"]
        assert ev["plan_cache_misses"] >= CACHE_MISS_MIN
        assert ev["plan_cache_hits"] == 0
        assert "cache" in diag["findings"][0]["remedy"]
        # journal records each miss as a cache.miss event on the serving path
        misses = [e for e in journal.snapshot()
                  if e["kind"] == "cache.miss"]
        assert len(misses) >= CACHE_MISS_MIN
    finally:
        ctx.shutdown()


def test_control_plane_churn_rule():
    bundle = _clean_bundle_template()
    bundle["journal"].append({"seq": 999, "ts_ms": 1, "kind": "lease.adopt",
                              "job_id": bundle["job_id"], "epoch": 2,
                              "attrs": {"prev_owner": "scheduler-dead"}})
    bundle["journal"].append({"seq": 1000, "ts_ms": 2,
                              "kind": "quarantine.enter",
                              "job_id": bundle["job_id"],
                              "attrs": {"executor_id": "exec-1"}})
    diag = diagnose(bundle)
    assert _rules(diag) == ["control-plane-churn"], diag["text"]
    ev = diag["findings"][0]["evidence"]
    assert ev["lease_adoptions"] == 1 and ev["quarantines"] == 1
    assert "lease" in diag["findings"][0]["remedy"]


def test_diagnose_is_deterministic_and_ranked():
    bundle = _clean_bundle_template()
    st = bundle["stages"][0]
    st["partition_bytes"] = {"0": 6 << 20,
                             **{str(p): 1 << 16 for p in range(1, 8)}}
    bundle["metrics"]["plan_cache_misses"] = 100
    bundle["metrics"]["plan_cache_hits"] = 0
    d1 = diagnose(copy.deepcopy(bundle))
    d2 = diagnose(copy.deepcopy(bundle))
    assert d1 == d2, "equal bundles must produce equal output"
    sev = [f["severity"] for f in d1["findings"]]
    assert sev == sorted(sev, reverse=True), "findings rank by severity"
    assert set(_rules(d1)) == {"shuffle-hotspot", "cache-miss-churn"}


# --------------------------------------------------------------------------
# fleet failover (chaos): one timeline across adoption, epoch marked
# --------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow  # exercised by run_checks.sh stage 4 (-m chaos)
def test_failover_forensics_single_timeline_with_epochs(tmp_path):
    from .test_fleet import (
        SQL,
        _AsyncQuery,
        _fleet_client,
        _make_fleet,
        _teardown_fleet,
        _wait_for,
    )

    kv, shards, executors = _make_fleet(tmp_path, concurrent_tasks=1)
    try:
        eps = [("127.0.0.1", s.port) for s in shards]
        c = _fleet_client(eps)
        plan = faults.FaultPlan.from_obj({"seed": 5, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 400, "times": -1}]})
        with faults.use_plan(plan):
            q = _AsyncQuery(c, SQL)
            q.start()
            _wait_for(lambda: shards[0].server._leases, 10.0,
                      "primary shard should claim the job lease at submit")
            job_id = next(iter(shards[0].server._leases))
            dead_sid = shards[0].server.scheduler_id
            shards[0].kill()  # in-process kill -9: no release, no goodbye
            q.join(timeout=60.0)
        assert not q.is_alive() and q.error is None, f"failover: {q.error}"

        survivor = shards[1].server
        bundle = assemble_forensics(survivor, job_id)
        assert bundle is not None and validate_bundle(bundle) == []
        tl = bundle["journal"]
        kinds = [e["kind"] for e in tl]
        assert "job.submitted" in kinds, "pre-failover history survives"
        acquire = next(e for e in tl if e["kind"] == "lease.acquire")
        adopt = next(e for e in tl if e["kind"] == "lease.adopt")
        assert acquire["epoch"] == 1
        assert adopt["epoch"] >= 2, "takeover must bump the fencing epoch"
        assert adopt["attrs"]["prev_owner"] == dead_sid
        assert adopt["attrs"]["scheduler_id"] == survivor.scheduler_id
        # every post-adoption decision is stamped with the new epoch
        after = tl[tl.index(adopt) + 1:]
        assert any(e["kind"] == "job.successful" for e in after)
        for e in after:
            if e["kind"].startswith(("job.", "lease.", "task.finish")):
                assert e.get("epoch", 0) >= adopt["epoch"], \
                    f"unfenced post-adoption event: {e}"
        # ... and the doctor calls out the control-plane churn, citing it
        diag = diagnose(bundle)
        assert "control-plane-churn" in _rules(diag)
        churn = next(f for f in diag["findings"]
                     if f["rule"] == "control-plane-churn")
        assert churn["evidence"]["lease_adoptions"] == 1
        c.shutdown()
    finally:
        _teardown_fleet(kv, shards, executors)


@pytest.mark.chaos
@pytest.mark.slow  # exercised by run_checks.sh stage 4 (-m chaos)
def test_checkpoint_carries_timeline_for_adoption(tmp_path):
    """The persisted graph embeds the journal timeline (epoch-tagged), so
    an adopter in a FRESH process — which has none of the dead owner's
    in-memory ring — still reconstructs the pre-failover record."""
    from arrow_ballista_tpu.scheduler.persistence import FileJobStateBackend

    ctx = _standalone({"ballista.shuffle.partitions": "2"},
                      num_executors=1)
    try:
        ctx.register_table("t", _table(np.random.default_rng(8), 2000))
        sched = ctx._standalone.scheduler
        sched.job_backend = FileJobStateBackend(str(tmp_path / "state"))
        ctx.sql("select g, sum(v) as s from t group by g").to_pandas()
        job_id = ctx._standalone.last_job_id
        graph = sched.jobs.get_graph(job_id)
        assert graph.journal, "checkpointed graphs must carry the timeline"
        kinds = [e["kind"] for e in graph.journal]
        assert "job.submitted" in kinds and "job.successful" in kinds, \
            "terminal events are journaled before the final checkpoint"

        # a blank journal (new process) seeded from the checkpoint serves
        # the identical timeline under the same job id
        persisted = [dict(e) for e in graph.journal]
        journal.reset()
        journal.seed_job(job_id, persisted)
        assert journal.job_timeline(job_id) == persisted
    finally:
        ctx.shutdown()
