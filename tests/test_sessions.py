"""Per-session isolation + the external SQL surface.

Parity: reference SessionManager (state/session_manager.rs:27-57 — one
DataFusion session per client with its own BallistaConfig) and the Flight
SQL endpoint (flight_sql.rs:83-911 — handshake/session, prepared
statements, execute, endpoints to executor partitions) that lets
non-library clients run SQL.
"""
import io
import json
import socket
import struct
import time

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.net import wire
from arrow_ballista_tpu.utils.config import BallistaConfig
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    sched = SchedulerNetService("127.0.0.1", 0,
                                config=BallistaConfig({"ballista.shuffle.partitions": "4"}))
    sched.start()
    ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                        work_dir=str(tmp_path_factory.mktemp("sess-exec")),
                        concurrent_tasks=4, executor_id="sess-exec-0")
    ex.start()
    yield sched
    ex.stop(notify=False)
    sched.stop()


def test_session_table_isolation(cluster):
    a = BallistaContext.remote("127.0.0.1", cluster.port)
    b = BallistaContext.remote("127.0.0.1", cluster.port)
    t = pa.table({"x": pa.array([1, 2, 3], type=pa.int64())})
    a.register_table("mine", t)
    # a sees it; b does not (private namespace per session)
    assert "mine" in a.sql("show tables").to_pandas().table_name.tolist()
    assert "mine" not in b.sql("show tables").to_pandas().table_name.tolist()
    out = a.sql("select sum(x) as s from mine").to_pandas()
    assert out.s[0] == 6
    a.shutdown()
    b.shutdown()


def test_session_config_isolation(cluster):
    """Two concurrent sessions with different shuffle partitions plan
    independently (the VERDICT done-criterion for per-session config)."""
    a = BallistaContext.remote("127.0.0.1", cluster.port,
                               BallistaConfig({"ballista.shuffle.partitions": "2"}))
    b = BallistaContext.remote("127.0.0.1", cluster.port,
                               BallistaConfig({"ballista.shuffle.partitions": "5"}))
    rng = np.random.default_rng(5)
    t = pa.table({"g": pa.array(rng.integers(0, 40, 4000).astype(np.int64)),
                  "v": pa.array(np.ones(4000, dtype=np.int64))})
    a.register_table("t", t)
    b.register_table("t", t)
    ga = a.sql("select g, sum(v) as s from t group by g order by g").to_pandas()
    gb = b.sql("select g, sum(v) as s from t group by g order by g").to_pandas()
    assert ga.s.sum() == 4000 and gb.s.sum() == 4000
    # the scheduler really planned with each session's partitioning: inspect
    # the last two jobs' graphs.  Adaptive exchange coalescing may collapse
    # the tiny reduce stage to ONE task at runtime — the session isolation
    # claim is about the PLANNED partitioning, which _orig_partitions
    # preserves when coalescing fires.
    graphs = [cluster.server.jobs.get_graph(j)
              for j in cluster.server.jobs.job_ids()]
    parts = sorted({g.stages[2].planned_partitions
                    for g in graphs if g is not None and len(g.stages) >= 2})
    assert 2 in parts and 5 in parts, f"stage partition counts seen: {parts}"
    a.shutdown()
    b.shutdown()


def test_prepared_statements(cluster):
    ctx = BallistaContext.remote("127.0.0.1", cluster.port)
    t = pa.table({"x": pa.array([5, 7], type=pa.int64())})
    ctx.register_table("p", t)
    sid = ctx._remote.session_id
    prep, _ = wire.call("127.0.0.1", cluster.port, "prepare",
                        {"session_id": sid, "sql": "select sum(x) as s from p"})
    assert prep["schema"][0]["name"] == "s"
    payload, _ = wire.call("127.0.0.1", cluster.port, "execute_query",
                           {"session_id": sid,
                            "statement_id": prep["statement_id"]})
    deadline = time.time() + 30
    while time.time() < deadline:
        st, _ = wire.call("127.0.0.1", cluster.port, "get_job_status",
                          {"job_id": payload["job_id"]})
        if st["state"] == "successful":
            break
        assert st["state"] not in ("failed", "cancelled"), st
        time.sleep(0.05)
    assert st["state"] == "successful"
    ctx.shutdown()


def test_expired_session_rejected(cluster):
    payload, _ = wire.call("127.0.0.1", cluster.port, "create_session", {})
    sid = payload["session_id"]
    wire.call("127.0.0.1", cluster.port, "remove_session", {"session_id": sid})
    with pytest.raises(wire.RemoteError):
        wire.call("127.0.0.1", cluster.port, "list_tables", {"session_id": sid})


def test_external_client_script(cluster, tmp_path):
    """The examples/ client (stdlib + pyarrow only) runs SQL end-to-end."""
    import subprocess
    import sys

    import pyarrow.parquet as pq

    data = tmp_path / "nums.parquet"
    pq.write_table(pa.table({"v": pa.array(range(100), type=pa.int64())}),
                   str(data))
    script = "examples/external_sql_client.py"
    out = subprocess.run(
        [sys.executable, script, "127.0.0.1", str(cluster.port),
         f"create external table nums stored as parquet location '{data}'",
         "select count(*) as n, sum(v) as s from nums"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "4950" in out.stdout and "100" in out.stdout
