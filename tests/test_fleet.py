"""Scheduler fleet HA: lease-based ownership, adoption, and failover.

Chaos suite for the multi-shard scheduler fleet (ISSUE 11): N schedulers
share one KV (cluster state + job checkpoints + TTL job leases), executors
multi-register and route statuses to the launching shard, and clients hold
an ordered endpoint list with transparent failover.  Scenarios:

- two live shards serve one client with shared cluster state;
- a shard killed mid-job (in-process kill() == kill -9, and a REAL
  SIGKILL'd subprocess shard) has its jobs adopted by a survivor, which
  resumes from the last checkpoint and drives to a bit-identical result;
- a partitioned shard that stops renewing (``scheduler.lease.renew``
  failpoint) is fenced out by the adopter's epoch bump — no double-drive;
- adoption racing completion (``scheduler.adopt.before_resume`` delay)
  releases the claim instead of re-driving a finished job;
- a non-owning shard redirects status polls to the lease owner and serves
  terminal results straight from the checkpoint.

All timings are scaled down (TTL 1.5 s, renew 0.4 s, adopt scan 0.4 s) so
every scenario resolves in seconds.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu import faults
from arrow_ballista_tpu.utils.config import BallistaConfig

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear()
    yield
    faults.clear()


# --------------------------------------------------------------------------
# fleet harness: shared KvServer + N scheduler shards + executors + client
# --------------------------------------------------------------------------

FLEET_CONF = {
    "ballista.shuffle.partitions": "4",
    # fast-failure RPC policy so failover scenarios stay seconds-long
    "ballista.rpc.connect.timeout.seconds": "1.0",
    "ballista.rpc.read.timeout.seconds": "10.0",
    "ballista.rpc.retry.base.seconds": "0.05",
    "ballista.rpc.retry.cap.seconds": "0.2",
    "ballista.rpc.retry.deadline.seconds": "1.5",
    "ballista.shuffle.local.host_match": "false",
    # scaled-down fleet timings: a dead shard's jobs must be adopted
    # within ~2 s (TTL 1.5 s + one 0.4 s adoption scan)
    "ballista.fleet.lease.ttl.seconds": "1.5",
    "ballista.fleet.lease.renew.seconds": "0.4",
    "ballista.fleet.adopt.interval.seconds": "0.4",
    "ballista.fleet.registry.stale.seconds": "5.0",
}

SQL = "select g, sum(v) as s, count(*) as n from t group by g order by g"


def _sched_config(adopt_interval_s=0.4):
    from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig

    return SchedulerConfig(task_distribution="round-robin",
                           executor_timeout_s=3.0,
                           reaper_interval_s=0.3,
                           fleet_lease_ttl_s=1.5,
                           fleet_lease_renew_s=0.4,
                           fleet_adopt_interval_s=adopt_interval_s,
                           fleet_registry_stale_s=5.0)


def _make_fleet(tmp_path, n_shards=2, n_executors=2, concurrent_tasks=4,
                adopt_interval_s=0.4):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.kv import MemoryKv
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    kv = KvServer(MemoryKv(), "127.0.0.1", 0)
    kv.start()
    url = f"kv://{kv.host}:{kv.port}"
    shards = []
    for _ in range(n_shards):
        s = SchedulerNetService("127.0.0.1", 0,
                                config=BallistaConfig(FLEET_CONF),
                                scheduler_config=_sched_config(adopt_interval_s),
                                cluster_url=url)
        s.start()
        shards.append(s)
    eps = [("127.0.0.1", s.port) for s in shards]
    executors = []
    for i in range(n_executors):
        work = tmp_path / f"exec{i}"
        work.mkdir()
        ex = ExecutorServer("127.0.0.1", eps[0][1], "127.0.0.1", 0,
                            work_dir=str(work),
                            concurrent_tasks=concurrent_tasks,
                            executor_id=f"fleet-exec-{i}",
                            config=BallistaConfig(FLEET_CONF),
                            heartbeat_interval_s=0.4,
                            scheduler_endpoints=eps)
        ex.start()
        executors.append(ex)
    return kv, shards, executors


def _teardown_fleet(kv, shards, executors):
    for ex in executors:
        try:
            ex.stop(notify=False)
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
    for s in shards:
        try:
            s.stop()  # idempotent after kill(): shutdown/stop re-run clean
        except Exception:  # noqa: BLE001
            pass
    try:
        kv.stop()
    except Exception:  # noqa: BLE001
        pass


def _fleet_client(eps, n=8000, groups=7, seed=11):
    from arrow_ballista_tpu.client.context import BallistaContext

    c = BallistaContext.remote(config=BallistaConfig(FLEET_CONF),
                               endpoints=eps)
    rng = np.random.default_rng(seed)
    c.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, groups, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    }))
    return c


def _frames_equal(got, expected):
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  expected.reset_index(drop=True),
                                  check_dtype=False)


def _wait_for(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


class _AsyncQuery(threading.Thread):
    """Run one SQL query off-thread so the test can kill shards mid-job."""

    def __init__(self, ctx, sql):
        super().__init__(name="fleet-query", daemon=True)
        self.ctx, self.sql = ctx, sql
        self.result = None
        self.error = None

    def run(self):
        try:
            self.result = self.ctx.sql(self.sql).to_pandas()
        except Exception as e:  # noqa: BLE001 — asserted by the test
            self.error = e


# --------------------------------------------------------------------------
# scenario 1: two shards, shared state, fleet-wide registry + autoscale
# --------------------------------------------------------------------------

def test_two_shard_fleet_serves_and_aggregates_registry(tmp_path):
    kv, shards, executors = _make_fleet(tmp_path)
    try:
        c = _fleet_client([("127.0.0.1", s.port) for s in shards])
        got = c.sql(SQL).to_pandas()
        again = c.sql(SQL).to_pandas()
        _frames_equal(got, again)

        # the lease loop publishes each shard into the shared registry;
        # after that, ANY shard's autoscale signal covers the whole fleet
        _wait_for(
            lambda: len(shards[0].server.autoscale_signal()["shards"]) == 2,
            5.0, "both shards should appear in the shared registry")
        for s in shards:
            sig = s.server.autoscale_signal()
            assert {x["scheduler_id"] for x in sig["shards"]} == \
                {sh.server.scheduler_id for sh in shards}
            assert all(x["endpoint"] for x in sig["shards"])
            assert sig["total_slots"] > 0
        c.shutdown()
    finally:
        _teardown_fleet(kv, shards, executors)


# --------------------------------------------------------------------------
# scenario 2: shard killed mid-job -> survivor adopts -> bit-identical
# --------------------------------------------------------------------------

def test_shard_killed_mid_job_survivor_adopts(tmp_path):
    kv, shards, executors = _make_fleet(tmp_path, concurrent_tasks=1)
    try:
        eps = [("127.0.0.1", s.port) for s in shards]
        c = _fleet_client(eps)
        baseline = c.sql(SQL).to_pandas()

        # stretch every task so the kill lands mid-job
        plan = faults.FaultPlan.from_obj({"seed": 5, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 400, "times": -1}]})
        with faults.use_plan(plan):
            q = _AsyncQuery(c, SQL)
            q.start()
            _wait_for(lambda: shards[0].server._leases, 10.0,
                      "primary shard should claim the job lease at submit")
            job_id = next(iter(shards[0].server._leases))
            # in-process kill -9: no lease release, no registry goodbye
            shards[0].kill()
            q.join(timeout=60.0)

        assert not q.is_alive(), "query never finished after the failover"
        assert q.error is None, f"query failed across failover: {q.error}"
        _frames_equal(q.result, baseline)
        # the survivor adopted and drove the job to terminal
        status = shards[1].server.jobs.get_status(job_id)
        assert status is not None and status.state == "successful"
        c.shutdown()
    finally:
        _teardown_fleet(kv, shards, executors)


# --------------------------------------------------------------------------
# scenario 3: partition (renewals suppressed) -> epoch fencing, one driver
# --------------------------------------------------------------------------

def test_partitioned_shard_is_fenced_no_double_drive(tmp_path):
    kv, shards, executors = _make_fleet(tmp_path, concurrent_tasks=1)
    try:
        eps = [("127.0.0.1", s.port) for s in shards]
        c = _fleet_client(eps)
        baseline = c.sql(SQL).to_pandas()

        a = shards[0].server
        b = shards[1].server
        plan = faults.FaultPlan.from_obj({"seed": 9, "rules": [
            # shard A stops renewing but keeps driving: simulated partition
            {"site": "scheduler.lease.renew", "action": "raise",
             "error": "timeout", "message": "injected partition",
             "match": {"scheduler_id": a.scheduler_id}, "times": -1},
            {"site": "executor.task.slow", "action": "delay",
             "delay_ms": 800, "times": -1},
        ]})
        with faults.use_plan(plan):
            q = _AsyncQuery(c, SQL)
            q.start()
            _wait_for(lambda: a._leases, 10.0,
                      "partitioned shard should claim the lease at submit")
            job_id = next(iter(a._leases))
            # lease expires unrenewed -> the survivor adopts it
            _wait_for(lambda: b.jobs.get_status(job_id) is not None, 15.0,
                      "survivor should adopt the partitioned shard's job")
            lease = b.job_backend.get_lease(job_id)
            if lease is not None:  # None == already completed and released
                assert lease.owner == b.scheduler_id
                assert lease.epoch >= 2, "takeover must bump the fencing epoch"
            # the ex-owner's next fenced checkpoint raises LeaseLost and it
            # abandons its local drive — that is the no-double-drive proof
            _wait_for(lambda: a.jobs.get_status(job_id) is None, 20.0,
                      "fenced ex-owner must abandon its local drive")
            q.join(timeout=90.0)

        assert not q.is_alive(), "query never finished after the partition"
        assert q.error is None, f"query failed across the partition: {q.error}"
        _frames_equal(q.result, baseline)
        status = b.jobs.get_status(job_id)
        assert status is not None and status.state == "successful"
        c.shutdown()
    finally:
        _teardown_fleet(kv, shards, executors)


# --------------------------------------------------------------------------
# scenario 4: adoption racing completion -> claim released, no re-drive
# --------------------------------------------------------------------------

def test_adoption_skips_job_that_already_completed(tmp_path):
    from arrow_ballista_tpu.scheduler.kv import JOB_LOCKS

    # adoption scans effectively disabled (60 s): the race is staged by hand
    kv, shards, executors = _make_fleet(tmp_path, adopt_interval_s=60.0)
    try:
        eps = [("127.0.0.1", s.port) for s in shards]
        c = _fleet_client(eps)
        c.sql(SQL).to_pandas()  # runs on shard A; checkpoints terminal graph

        backend = shards[1].server.job_backend
        [job_id] = backend.list_jobs()
        assert backend.get_lease(job_id) is None, \
            "completion must release the job lease"

        # ghost owner that died right after finishing the job but before
        # releasing: expired lease + terminal checkpoint
        backend.store.put(JOB_LOCKS, job_id, json.dumps(
            {"owner": "ghost-shard", "epoch": 7,
             "ts": time.time() - 60.0, "endpoint": "127.0.0.1:1"}))
        plan = faults.FaultPlan.from_obj({"seed": 2, "rules": [{
            "site": "scheduler.adopt.before_resume", "action": "delay",
            "delay_ms": 150, "match": {"job_id": job_id}, "times": 1}]})
        with faults.use_plan(plan):
            adopted = shards[1].server.adopt_expired_jobs()

        assert adopted == []
        assert plan.schedule() == \
            (("scheduler.adopt.before_resume", 0, 1, "delay"),)
        # the claim was dropped, not left dangling as an expired lease,
        # and the finished job was NOT re-driven
        assert backend.get_lease(job_id) is None
        assert shards[1].server.jobs.get_status(job_id) is None
        c.shutdown()
    finally:
        _teardown_fleet(kv, shards, executors)


# --------------------------------------------------------------------------
# scenario 5: non-owning shard redirects polls / serves terminal checkpoints
# --------------------------------------------------------------------------

def test_foreign_status_redirect_and_terminal_serve(tmp_path):
    from arrow_ballista_tpu.net import wire

    kv, shards, executors = _make_fleet(tmp_path, concurrent_tasks=1)
    try:
        eps = [("127.0.0.1", s.port) for s in shards]
        c = _fleet_client(eps)
        a = shards[0].server

        plan = faults.FaultPlan.from_obj({"seed": 4, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 400, "times": -1}]})
        with faults.use_plan(plan):
            q = _AsyncQuery(c, SQL)
            q.start()
            _wait_for(lambda: a._leases, 10.0,
                      "owner shard should claim the lease at submit")
            job_id = next(iter(a._leases))
            # while the job runs on A, B redirects to the lease owner
            payload, _ = wire.call("127.0.0.1", shards[1].port,
                                   "get_job_status", {"job_id": job_id})
            assert payload["state"] == "not_found"
            assert payload["owner"] == a.scheduler_id
            assert payload["endpoint"] == f"127.0.0.1:{shards[0].port}"
            q.join(timeout=60.0)
        assert q.error is None, f"query failed: {q.error}"

        # after completion the lease is gone; B serves the status (with
        # result locations + schema) straight from the shared checkpoint
        payload, _ = wire.call("127.0.0.1", shards[1].port,
                               "get_job_status", {"job_id": job_id})
        assert payload["state"] == "successful"
        assert payload["locations"], "terminal serve must carry locations"
        assert payload["schema"], "terminal serve must carry the schema"
        c.shutdown()
    finally:
        _teardown_fleet(kv, shards, executors)


# --------------------------------------------------------------------------
# scenario 6: REAL process kill (SIGKILL) of a shard -> live failover
# --------------------------------------------------------------------------

_CHILD_SHARD_SRC = """
import json, sys, threading
from arrow_ballista_tpu.utils.config import BallistaConfig
from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService
from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig

conf = json.loads(sys.argv[1])
s = SchedulerNetService(
    "127.0.0.1", 0, config=BallistaConfig(conf),
    scheduler_config=SchedulerConfig(
        task_distribution="round-robin", executor_timeout_s=3.0,
        reaper_interval_s=0.3, fleet_lease_ttl_s=1.5,
        fleet_lease_renew_s=0.4, fleet_adopt_interval_s=0.4,
        fleet_registry_stale_s=5.0),
    cluster_url=sys.argv[2])
s.start()
print("READY", s.port, s.server.scheduler_id, flush=True)
threading.Event().wait()
"""


def _spawn_child_shard(url, tmp_path, timeout=90.0):
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SHARD_SRC, json.dumps(FLEET_CONF), url],
        stdout=subprocess.PIPE,
        stderr=open(tmp_path / "child-shard.log", "w"),
        text=True, env=dict(os.environ))
    out = {}

    def rd():
        out["line"] = proc.stdout.readline()

    t = threading.Thread(target=rd, daemon=True)
    t.start()
    t.join(timeout)
    line = out.get("line", "")
    if not line.startswith("READY"):
        proc.kill()
        raise AssertionError(f"child shard failed to start: {line!r} "
                             f"(see {tmp_path / 'child-shard.log'})")
    _, port, scheduler_id = line.split()
    return proc, int(port), scheduler_id


def test_real_process_sigkill_failover(tmp_path):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.kv import MemoryKv
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    kv = KvServer(MemoryKv(), "127.0.0.1", 0)
    kv.start()
    url = f"kv://{kv.host}:{kv.port}"
    proc, child_port, child_sid = _spawn_child_shard(url, tmp_path)
    survivor = SchedulerNetService("127.0.0.1", 0,
                                   config=BallistaConfig(FLEET_CONF),
                                   scheduler_config=_sched_config(),
                                   cluster_url=url)
    survivor.start()
    eps = [("127.0.0.1", child_port), ("127.0.0.1", survivor.port)]
    executors = []
    try:
        for i in range(2):
            work = tmp_path / f"exec{i}"
            work.mkdir()
            ex = ExecutorServer("127.0.0.1", child_port, "127.0.0.1", 0,
                                work_dir=str(work), concurrent_tasks=1,
                                executor_id=f"fleet-exec-{i}",
                                config=BallistaConfig(FLEET_CONF),
                                heartbeat_interval_s=0.4,
                                scheduler_endpoints=eps)
            ex.start()
            executors.append(ex)
        c = _fleet_client(eps)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 6, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 400, "times": -1}]})
        with faults.use_plan(plan):
            q = _AsyncQuery(c, SQL)
            q.start()
            backend = survivor.server.job_backend
            _wait_for(
                lambda: any(l.owner == child_sid for l in backend.leases()),
                15.0, "child shard should claim the job lease at submit")
            proc.kill()  # SIGKILL: the real thing, not a simulation
            proc.wait(timeout=10.0)
            q.join(timeout=60.0)

        assert not q.is_alive(), "query never finished after SIGKILL failover"
        assert q.error is None, f"query failed across SIGKILL: {q.error}"
        _frames_equal(q.result, baseline)
        # the in-process survivor adopted the dead process's job
        jobs = list(survivor.server.jobs._graphs)
        assert any(
            survivor.server.jobs.get_status(j) is not None and
            survivor.server.jobs.get_status(j).state == "successful"
            for j in jobs), "survivor should hold the adopted job terminal"
        c.shutdown()
    finally:
        try:
            proc.kill()
        except Exception:  # noqa: BLE001
            pass
        _teardown_fleet(kv, [survivor], executors)
