"""Round-2 hardening: reliable status reporting, atomic lock takeover,
heartbeat auto re-registration, 64-bit data frames, data-plane auth.

Reference parity: executor_server.rs status batching/retry, grpc.rs:174-241
heartbeat re-register, cluster/storage lock semantics, flight_service.rs
bearer-token auth.
"""
import json
import os
import socket
import threading
import time

import pytest

from arrow_ballista_tpu.net import wire
from arrow_ballista_tpu.scheduler.persistence import FileJobStateBackend
from arrow_ballista_tpu.scheduler.types import (
    ExecutorHeartbeat,
    ExecutorMetadata,
    TaskId,
    TaskStatus,
)


# --------------------------------------------------------------------------
# wire framing
# --------------------------------------------------------------------------


def test_wire_header_is_64bit():
    # a 6 GiB binary length must survive header round-trip (u32 truncated it)
    big = 6 << 30
    hdr = wire._HDR.pack(10, big)
    jlen, blen = wire._HDR.unpack(hdr)
    assert jlen == 10 and blen == big
    assert wire.MAX_BIN > (4 << 30)


def test_wire_roundtrip_with_binary():
    a, b = socket.socketpair()
    try:
        payload = os.urandom(1 << 16)
        wire.send_frame(a, {"method": "x"}, payload)
        obj, binary = wire.recv_frame(b)
        assert obj == {"method": "x"} and binary == payload
    finally:
        a.close()
        b.close()


# --------------------------------------------------------------------------
# push-mode status reporting survives scheduler outages
# --------------------------------------------------------------------------


class _FlakyScheduler:
    def __init__(self, fail_times: int):
        self.fail_times = fail_times
        self.got = []
        self.lock = threading.Lock()

    def update_task_status(self, executor_id, statuses):
        with self.lock:
            if self.fail_times > 0:
                self.fail_times -= 1
                raise ConnectionError("scheduler briefly unreachable")
            self.got.extend(statuses)

    def heartbeat(self, *a, **k):
        pass

    def register_executor(self, *a, **k):
        pass

    def executor_stopped(self, *a, **k):
        pass


def test_push_status_retries_until_delivered(tmp_path):
    from arrow_ballista_tpu.executor.server import ExecutorServer

    srv = ExecutorServer("127.0.0.1", 1, port=0, work_dir=str(tmp_path),
                         policy="push")
    flaky = _FlakyScheduler(fail_times=2)
    srv.scheduler = flaky
    srv.start(register=False)
    try:
        st = TaskStatus(TaskId("jobz", 1, 0), srv.metadata.executor_id, "success")
        srv._report_status(st)
        deadline = time.time() + 15
        while not flaky.got and time.time() < deadline:
            time.sleep(0.05)
        assert flaky.got and flaky.got[0].task.job_id == "jobz"
        assert flaky.fail_times == 0  # the transient failures actually happened
    finally:
        srv.stop(notify=False)


# --------------------------------------------------------------------------
# stale-lock takeover is atomic
# --------------------------------------------------------------------------


def test_stale_lock_single_winner(tmp_path):
    backend = FileJobStateBackend(str(tmp_path))
    lock = os.path.join(str(tmp_path), "jobr.lock")
    with open(lock, "w") as f:
        json.dump({"owner": "dead-scheduler", "ts": time.time() - 3600}, f)

    results = {}
    barrier = threading.Barrier(8)

    def contend(i):
        barrier.wait()
        results[i] = backend.try_acquire_job("jobr", f"sched-{i}",
                                             stale_after_s=60.0)

    threads = [threading.Thread(target=contend, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(1 for ok in results.values() if ok) == 1
    # the winner's lock is in place and fresh
    with open(lock) as f:
        holder = json.load(f)
    winner = [i for i, ok in results.items() if ok][0]
    assert holder["owner"] == f"sched-{winner}"


def test_fresh_lock_not_stolen(tmp_path):
    backend = FileJobStateBackend(str(tmp_path))
    assert backend.try_acquire_job("jobf", "sched-a")
    assert not backend.try_acquire_job("jobf", "sched-b")
    assert backend.try_acquire_job("jobf", "sched-a")  # reentrant for owner


# --------------------------------------------------------------------------
# heartbeat auto re-registration
# --------------------------------------------------------------------------


def test_heartbeat_reregisters_unknown_executor():
    from arrow_ballista_tpu.scheduler.scheduler import SchedulerServer, TaskLauncher

    class NullLauncher(TaskLauncher):
        def launch_tasks(self, executor_id, tasks):
            pass

        def cancel_tasks(self, executor_id, job_id):
            pass

        def stop(self):
            pass

    server = SchedulerServer(NullLauncher())
    server.init(start_reaper=False)
    try:
        meta = ExecutorMetadata("exec-zombie", host="h1", port=7000, task_slots=2)
        # no registration — straight to heartbeat, as after a scheduler restart
        server.heartbeat(ExecutorHeartbeat("exec-zombie", metadata=meta))
        got = server.cluster.get_executor("exec-zombie")
        assert got is not None and got.host == "h1" and got.task_slots == 2
        # terminating executors are not reaped while still heartbeating
        server.heartbeat(ExecutorHeartbeat("exec-zombie", status="terminating",
                                           metadata=meta))
        assert "exec-zombie" not in server.cluster.expired_executors(60.0)
        assert "exec-zombie" not in server.cluster.alive_executors(60.0)
    finally:
        server.shutdown()


# --------------------------------------------------------------------------
# data-plane auth token (python fallback handler)
# --------------------------------------------------------------------------


def test_data_plane_token(tmp_path, monkeypatch):
    monkeypatch.setenv("BALLISTA_DATA_PLANE_TOKEN", "sekrit")
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.utils.errors import ExecutionError

    srv = ExecutorServer("127.0.0.1", 1, port=0, work_dir=str(tmp_path),
                         policy="push")
    try:
        p = tmp_path / "jobt" / "f.arrow"
        p.parent.mkdir(parents=True)
        p.write_bytes(b"data")
        with pytest.raises(ExecutionError):
            srv._fetch_partition({"path": str(p)}, b"")
        with pytest.raises(ExecutionError):
            srv._fetch_partition({"path": str(p), "token": "wrong"}, b"")
        payload, data = srv._fetch_partition(
            {"path": str(p), "token": "sekrit"}, b"")
        assert data == b"data"
    finally:
        srv.stop(notify=False)


# --------------------------------------------------------------------------
# bounded-concurrency remote shuffle fetch
# --------------------------------------------------------------------------


def test_concurrent_remote_fetch(tmp_path):
    """Many remote locations fetch in parallel (reference: <=50 concurrent
    Flight fetches, shuffle_reader.rs:123) and results stay correct."""
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.models.batch import ColumnBatch
    from arrow_ballista_tpu.models.ipc import write_ipc_file
    from arrow_ballista_tpu.models.schema import Field, INT64, Schema
    from arrow_ballista_tpu.net.rpc import RpcServer
    from arrow_ballista_tpu.ops.physical import TaskContext
    from arrow_ballista_tpu.ops.shuffle import PartitionLocation, ShuffleReaderExec

    schema = Schema([Field("v", INT64)])
    n_locs = 12
    paths = []
    for i in range(n_locs):
        b = ColumnBatch.from_numpy(schema, {"v": np.full(4, i, dtype=np.int64)})
        p = str(tmp_path / f"data-{i}.arrow")
        write_ipc_file(b, p)
        paths.append(p)

    inflight = {"now": 0, "max": 0}
    lock = threading.Lock()

    def fetch(payload, _bin):
        with lock:
            inflight["now"] += 1
            inflight["max"] = max(inflight["max"], inflight["now"])
        time.sleep(0.05)  # hold the slot so overlap is observable
        with open(payload["path"], "rb") as f:
            data = f.read()
        with lock:
            inflight["now"] -= 1
        return {"num_bytes": len(data)}, data

    server = RpcServer("127.0.0.1", 0)
    server.register("fetch_partition", fetch)
    server.start()
    try:
        locs = [PartitionLocation("exec-remote", i, 0, paths[i], num_rows=4,
                                  host="127.0.0.1", port=server.port)
                for i in range(n_locs)]
        reader = ShuffleReaderExec(1, schema, 1, {0: locs})
        ctx = TaskContext(executor_id="exec-local")
        batches = reader.execute(0, ctx)
        vals = sorted(int(x) for b in batches
                      for x in np.asarray(b.columns["v"])[np.asarray(b.mask)])
        assert vals == sorted(int(v) for i in range(n_locs) for v in [i] * 4)
        assert inflight["max"] > 1  # fetches actually overlapped
    finally:
        server.stop()
