"""Deployment entry points as REAL processes: scheduler_daemon +
executor_daemon subprocesses, remote client over the wire, SIGTERM drain.

This is the path docker-compose/helm run (reference scheduler_process.rs /
executor_process.rs); everything else in the suite exercises the same
machinery in-process."""
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(mod, *args):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", mod, *args], cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _wait_ping(port, deadline_s=60):
    from arrow_ballista_tpu.net import wire

    deadline = time.monotonic() + deadline_s
    while True:
        try:
            wire.call("127.0.0.1", port, "ping", timeout=2.0)
            return
        except Exception:  # noqa: BLE001
            if time.monotonic() > deadline:
                raise
            time.sleep(0.3)


def test_daemons_end_to_end(tmp_path):
    port = _free_port()
    rest = _free_port()
    sched = _spawn("arrow_ballista_tpu.scheduler_daemon",
                   "--bind-host", "127.0.0.1", "--bind-port", str(port),
                   "--rest-port", str(rest),
                   "--state-dir", str(tmp_path / "state"))
    ex = None
    try:
        _wait_ping(port)
        ex = _spawn("arrow_ballista_tpu.executor_daemon",
                    "--scheduler-port", str(port),
                    "--work-dir", str(tmp_path / "work"))

        from arrow_ballista_tpu.client.context import BallistaContext
        from arrow_ballista_tpu.utils.config import BallistaConfig

        ctx = BallistaContext.remote("127.0.0.1", port, BallistaConfig(
            {"ballista.shuffle.partitions": "2",
             "ballista.job.timeout.seconds": "120"}))
        rng = np.random.default_rng(1)
        ctx.register_table("t", pa.table({
            "g": pa.array(rng.integers(0, 5, 5000).astype(np.int64)),
            "v": pa.array(rng.integers(0, 100, 5000).astype(np.int64))}))
        # executor registration is async — retry until slots exist
        deadline = time.monotonic() + 60
        while True:
            try:
                out = ctx.sql("select g, sum(v) s, count(*) n from t "
                              "group by g order by g").to_pandas()
                break
            except Exception:  # noqa: BLE001
                if time.monotonic() > deadline:
                    raise
                time.sleep(1)
        assert len(out) == 5 and out.n.sum() == 5000

        # web ui + api live on the daemon's rest port
        import json
        import urllib.request

        jobs = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{rest}/api/jobs", timeout=10))
        assert any(j["state"] == "successful" for j in jobs)

        ctx.shutdown()
    finally:
        for proc, name in ((ex, "executor"), (sched, "scheduler")):
            if proc is None:
                continue
            proc.send_signal(signal.SIGTERM)
            try:
                # generous: the suite shares one CPU core and a graceful
                # drain competes with every other test's work
                rc = proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                out = proc.communicate()[0]
                raise AssertionError(
                    f"{name} did not exit on SIGTERM\n{out[-2000:]}")
            assert rc == 0, f"{name} exited rc={rc}\n" \
                            f"{proc.communicate()[0][-2000:]}"
