"""Deployment entry points as REAL processes: scheduler_daemon +
executor_daemon subprocesses, remote client over the wire, SIGTERM drain.

This is the path docker-compose/helm run (reference scheduler_process.rs /
executor_process.rs); everything else in the suite exercises the same
machinery in-process."""
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(mod, *args, log_dir=None, env_extra=None):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    # daemon output goes to a FILE, never a PIPE: an undrained pipe fills
    # at ~64KB and blocks the daemon mid-log (observed: the scheduler froze
    # and stopped accepting connections); proc._log_path is read back for
    # failure messages
    import tempfile

    log = tempfile.NamedTemporaryFile(
        mode="w", dir=log_dir, prefix=f"{mod.rsplit('.', 1)[-1]}-",
        suffix=".log", delete=False)
    proc = subprocess.Popen(
        [sys.executable, "-m", mod, *args], cwd=REPO, env=env,
        stdout=log, stderr=subprocess.STDOUT, text=True)
    proc._log_path = log.name
    return proc


def _log_tail(proc, n=2000):
    try:
        with open(proc._log_path) as f:
            return f.read()[-n:]
    except OSError:
        return "<no log>"


def _wait_ping(port, deadline_s=60):
    from arrow_ballista_tpu.net import wire

    deadline = time.monotonic() + deadline_s
    while True:
        try:
            wire.call("127.0.0.1", port, "ping", timeout=2.0)
            return
        except Exception:  # noqa: BLE001
            if time.monotonic() > deadline:
                raise
            time.sleep(0.3)


def test_daemons_end_to_end(tmp_path):
    port = _free_port()
    rest = _free_port()
    sched = _spawn("arrow_ballista_tpu.scheduler_daemon",
                   "--bind-host", "127.0.0.1", "--bind-port", str(port),
                   "--rest-port", str(rest),
                   "--state-dir", str(tmp_path / "state"),
                   log_dir=str(tmp_path))
    ex = None
    try:
        _wait_ping(port)
        ex = _spawn("arrow_ballista_tpu.executor_daemon",
                    "--scheduler-port", str(port),
                    "--work-dir", str(tmp_path / "work"),
                    log_dir=str(tmp_path))

        from arrow_ballista_tpu.client.context import BallistaContext
        from arrow_ballista_tpu.utils.config import BallistaConfig

        ctx = BallistaContext.remote("127.0.0.1", port, BallistaConfig(
            {"ballista.shuffle.partitions": "2",
             "ballista.job.timeout.seconds": "120"}))
        rng = np.random.default_rng(1)
        ctx.register_table("t", pa.table({
            "g": pa.array(rng.integers(0, 5, 5000).astype(np.int64)),
            "v": pa.array(rng.integers(0, 100, 5000).astype(np.int64))}))
        # executor registration is async — retry until slots exist
        deadline = time.monotonic() + 60
        while True:
            try:
                out = ctx.sql("select g, sum(v) s, count(*) n from t "
                              "group by g order by g").to_pandas()
                break
            except Exception:  # noqa: BLE001
                if time.monotonic() > deadline:
                    raise
                time.sleep(1)
        assert len(out) == 5 and out.n.sum() == 5000

        # web ui + api live on the daemon's rest port
        import json
        import urllib.request

        jobs = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{rest}/api/jobs", timeout=10))
        assert any(j["state"] == "successful" for j in jobs)

        ctx.shutdown()
    finally:
        for proc, name in ((ex, "executor"), (sched, "scheduler")):
            if proc is None:
                continue
            proc.send_signal(signal.SIGTERM)
            try:
                # generous: the suite shares one CPU core and a graceful
                # drain competes with every other test's work
                rc = proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                raise AssertionError(
                    f"{name} did not exit on SIGTERM\n{_log_tail(proc)}")
            assert rc == 0, f"{name} exited rc={rc}\n{_log_tail(proc)}"


def test_multihost_hybrid_exchange_real_processes(tmp_path):
    """VERDICT item: the hybrid exchange (mesh WITHIN a host, file shuffle
    ACROSS hosts) in REAL processes — 2 executor daemons, each a virtual
    4-device 'host', results bit-identical to the plain file path."""
    port = _free_port()
    sched = _spawn("arrow_ballista_tpu.scheduler_daemon",
                   "--bind-host", "127.0.0.1", "--bind-port", str(port),
                   "--rest-port", "-1",
                   "--state-dir", str(tmp_path / "state"),
                   log_dir=str(tmp_path))
    exes = []
    try:
        _wait_ping(port)
        for i in range(2):
            exes.append(_spawn(
                "arrow_ballista_tpu.executor_daemon",
                "--scheduler-port", str(port),
                "--work-dir", str(tmp_path / f"work{i}"),
                "--concurrent-tasks", "2", log_dir=str(tmp_path),
                env_extra={
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}))

        from arrow_ballista_tpu.client.context import BallistaContext
        from arrow_ballista_tpu.utils.config import BallistaConfig

        rng = np.random.default_rng(5)
        n = 20_000
        tbl = pa.table({
            "g": pa.array(rng.integers(0, 50, n).astype(np.int64)),
            "k": pa.array(rng.integers(0, 200, n).astype(np.int64)),
            "v": pa.array(rng.integers(0, 1000, n).astype(np.int64))})
        dim = pa.table({
            "k": pa.array(np.arange(200, dtype=np.int64)),
            "w": pa.array(rng.integers(0, 9, 200).astype(np.int64))})

        def run(settings):
            ctx = BallistaContext.remote("127.0.0.1", port, BallistaConfig({
                "ballista.shuffle.partitions": "4",
                "ballista.job.timeout.seconds": "180", **settings}))
            ctx.register_table("t", tbl)
            ctx.register_table("d", dim)
            deadline = time.monotonic() + 90
            while True:  # executors register async
                try:
                    agg = ctx.sql("select g, sum(v) s, count(*) c from t "
                                  "group by g order by g").to_pandas()
                    break
                except Exception:  # noqa: BLE001
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(1)
            join = ctx.sql(
                "select d.w as w, sum(t.v) s from t join d on t.k = d.k "
                "group by d.w order by w").to_pandas()
            ctx.shutdown()
            return agg, join

        plain_agg, plain_join = run({})
        hyb_agg, hyb_join = run({"ballista.shuffle.mesh": "true",
                                 "ballista.shuffle.mesh.hybrid": "true"})
        assert plain_agg.equals(hyb_agg)
        assert plain_join.equals(hyb_join)
    finally:
        for proc in exes + [sched]:
            proc.send_signal(signal.SIGTERM)
        for proc in exes + [sched]:
            try:
                proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
