"""Physical operator tests with real mini-data vs pandas oracles
(modeled on the reference's operator unit tests, e.g.
shuffle_writer.rs:437-532, with TempDir-scale data)."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu import BallistaConfig, Field, INT64, STRING, Schema, decimal
from arrow_ballista_tpu.models import expr as E
from arrow_ballista_tpu.ops.operators import (
    AggSpec,
    FilterExec,
    HashAggregateExec,
    JoinExec,
    LimitExec,
    ProjectionExec,
    SortExec,
)
from arrow_ballista_tpu.ops.physical import MemoryScanExec, TaskContext


def ctx():
    return TaskContext(config=BallistaConfig())


def lineitem_like(n=500, seed=7):
    # logical values: decimal columns carry dollars (scan scales to cents)
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "k": rng.integers(0, 50, n).astype(np.int64),
        "qty": (rng.integers(100, 5000, n) / 100.0),
        "price": (rng.integers(1000, 100000, n) / 100.0),
        "flag": rng.choice(["A", "N", "R"], n),
    })


SCHEMA = Schema([
    Field("k", INT64), Field("qty", decimal(2)), Field("price", decimal(2)),
    Field("flag", STRING),
])


def scan_of(df, partitions=2):
    return MemoryScanExec(SCHEMA, pa.Table.from_pandas(df), partitions)


def run_all(plan, c=None):
    c = c or ctx()
    out = []
    for p in range(plan.output_partition_count()):
        out.extend(plan.execute(p, c))
    frames = [b.to_pandas() for b in out]
    return pd.concat(frames, ignore_index=True)


def test_scan_roundtrip():
    df = lineitem_like()
    got = run_all(scan_of(df, 3))
    assert len(got) == len(df)
    np.testing.assert_array_equal(np.sort(got["k"]), np.sort(df["k"]))


def test_filter_and_project():
    df = lineitem_like()
    plan = FilterExec(scan_of(df), E.BinOp(">", E.Column("qty"), E.Lit(30.0)))
    plan = ProjectionExec(plan, [(E.Column("k"), "k"),
                                 (E.BinOp("*", E.Column("price"), E.Column("qty")), "v")])
    got = run_all(plan).sort_values(["k", "v"]).reset_index(drop=True)
    exp_mask = df["qty"] > 30.0
    exp = pd.DataFrame({
        "k": df["k"][exp_mask],
        "v": df["price"][exp_mask] * df["qty"][exp_mask],
    }).sort_values(["k", "v"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, atol=1e-6)


def test_aggregate_partial_final_matches_pandas():
    df = lineitem_like()
    scan = scan_of(df, 2)
    partial = HashAggregateExec(
        scan,
        [(E.Column("flag"), "flag")],
        [AggSpec("sum", E.Column("qty"), "s"), AggSpec("count", None, "c"),
         AggSpec("min", E.Column("price"), "mn")],
        mode="partial",
    )
    # merge partials in a single final (simulating post-shuffle single partition)
    from arrow_ballista_tpu.ops.operators import CoalescePartitionsExec

    final = HashAggregateExec(
        CoalescePartitionsExec(partial),
        [(E.Column("flag"), "flag")],
        [AggSpec("sum", E.Column("qty"), "s"), AggSpec("count", None, "c"),
         AggSpec("min", E.Column("price"), "mn")],
        mode="final",
    )
    got = run_all(final).sort_values("flag").reset_index(drop=True)
    exp = (df.groupby("flag", as_index=False)
           .agg(s=("qty", "sum"), c=("qty", "count"), mn=("price", "min"))
           .sort_values("flag").reset_index(drop=True))
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, atol=1e-6)


def test_global_aggregate_empty_input_returns_one_row():
    df = lineitem_like(10)
    plan = FilterExec(scan_of(df, 1), E.BinOp(">", E.Column("qty"), E.Lit(10**9)))
    agg = HashAggregateExec(plan, [], [AggSpec("count", None, "c")], mode="single")
    got = run_all(agg)
    assert len(got) == 1 and got["c"][0] == 0


def test_inner_join_matches_pandas():
    left = pd.DataFrame({"k": np.array([1, 2, 2, 3, 5], np.int64),
                         "lv": np.array([10, 20, 21, 30, 50], np.int64)})
    right = pd.DataFrame({"rk": np.array([2, 2, 3, 4], np.int64),
                          "rv": np.array([200, 201, 300, 400], np.int64)})
    ls = Schema([Field("k", INT64), Field("lv", INT64)])
    rs = Schema([Field("rk", INT64), Field("rv", INT64)])
    j = JoinExec(
        MemoryScanExec(ls, pa.Table.from_pandas(left), 1),
        MemoryScanExec(rs, pa.Table.from_pandas(right), 1),
        on=[(E.Column("k"), E.Column("rk"))], join_type="inner", dist="broadcast",
    )
    got = run_all(j).sort_values(["k", "lv", "rv"]).reset_index(drop=True)
    exp = (left.merge(right, left_on="k", right_on="rk")
           .sort_values(["k", "lv", "rv"]).reset_index(drop=True))
    pd.testing.assert_frame_equal(got[["k", "lv", "rk", "rv"]], exp[["k", "lv", "rk", "rv"]],
                                  check_dtype=False)


def test_semi_and_anti_join():
    left = pd.DataFrame({"k": np.array([1, 2, 3, 4], np.int64)})
    right = pd.DataFrame({"rk": np.array([2, 4, 4], np.int64)})
    ls = Schema([Field("k", INT64)])
    rs = Schema([Field("rk", INT64)])
    mk = lambda jt: JoinExec(
        MemoryScanExec(ls, pa.Table.from_pandas(left), 1),
        MemoryScanExec(rs, pa.Table.from_pandas(right), 1),
        on=[(E.Column("k"), E.Column("rk"))], join_type=jt, dist="broadcast",
    )
    semi = run_all(mk("semi"))["k"].tolist()
    anti = run_all(mk("anti"))["k"].tolist()
    assert sorted(semi) == [2, 4]
    assert sorted(anti) == [1, 3]


def test_left_join_keeps_unmatched():
    left = pd.DataFrame({"k": np.array([1, 2], np.int64)})
    right = pd.DataFrame({"rk": np.array([2], np.int64), "rv": np.array([7], np.int64)})
    j = JoinExec(
        MemoryScanExec(Schema([Field("k", INT64)]), pa.Table.from_pandas(left), 1),
        MemoryScanExec(Schema([Field("rk", INT64), Field("rv", INT64)]),
                       pa.Table.from_pandas(right), 1),
        on=[(E.Column("k"), E.Column("rk"))], join_type="left", dist="broadcast",
    )
    got = run_all(j).sort_values("k").reset_index(drop=True)
    assert len(got) == 2
    assert got["rv"].tolist()[1] == 7


def test_join_with_residual_filter():
    left = pd.DataFrame({"k": np.array([1, 1, 2], np.int64), "lv": np.array([5, 15, 9], np.int64)})
    right = pd.DataFrame({"rk": np.array([1, 2], np.int64), "rv": np.array([10, 10], np.int64)})
    j = JoinExec(
        MemoryScanExec(Schema([Field("k", INT64), Field("lv", INT64)]), pa.Table.from_pandas(left), 1),
        MemoryScanExec(Schema([Field("rk", INT64), Field("rv", INT64)]), pa.Table.from_pandas(right), 1),
        on=[(E.Column("k"), E.Column("rk"))], join_type="inner", dist="broadcast",
        filter=E.BinOp(">", E.Column("lv"), E.Column("rv")),
    )
    got = run_all(j)
    assert got[["lv"]].values.tolist() == [[15]]


def test_sort_with_fetch():
    df = lineitem_like(100)
    plan = SortExec(scan_of(df, 2), [(E.Column("qty"), False), (E.Column("k"), True)], fetch=5)
    got = run_all(plan)
    exp = df.sort_values(["qty", "k"], ascending=[False, True]).head(5)
    np.testing.assert_array_equal(got["k"].to_numpy(), exp["k"].to_numpy())


def test_limit():
    df = lineitem_like(100)
    got = run_all(LimitExec(scan_of(df, 2), 7))
    assert len(got) == 7


def test_string_sort_via_codes():
    df = pd.DataFrame({"flag": ["R", "A", "N", "A"], "v": np.arange(4, dtype=np.int64)})
    s = Schema([Field("flag", STRING), Field("v", INT64)])
    plan = SortExec(MemoryScanExec(s, pa.Table.from_pandas(df), 1),
                    [(E.Column("flag"), True)])
    got = run_all(plan)
    assert got["flag"].tolist() == ["A", "A", "N", "R"]


def test_aggregate_adaptive_capacity():
    """High-cardinality GROUP BY beyond ballista.agg.capacity must succeed
    via power-of-two recompilation (the join path's bucketed-recompile
    discipline applied to aggregation)."""
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.config import BallistaConfig

    n = 5000  # distinct keys far above the configured capacity of 16
    ctx = BallistaContext.local(BallistaConfig({"ballista.agg.capacity": "16"}))
    ctx.register_table("big", pa.table({
        "k": pa.array(np.arange(n, dtype=np.int64)),
        "v": pa.array(np.ones(n, dtype=np.int64)),
    }))
    out = ctx.sql("select k, sum(v) as s from big group by k").to_pandas()
    assert len(out) == n
    assert out.s.sum() == n


def test_count_literal_operand():
    """count(1) / sum(literal): scalar-compiled operands broadcast to rows
    (regression: examples/standalone_sql.py hit a 0-dim index error)."""
    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    ctx.register_table("t", pa.table({"g": np.arange(30, dtype=np.int64) % 3}))
    out = ctx.sql("select g, count(1) as n, sum(2) as s from t "
                  "group by g order by g").to_pandas()
    assert out.n.tolist() == [10, 10, 10]
    assert out.s.tolist() == [20, 20, 20]
    # literal group keys broadcast too
    out2 = ctx.sql("select 7 as k, count(*) as n from t group by k").to_pandas()
    assert out2.k.tolist() == [7] and out2.n.tolist() == [30]


def test_partial_agg_passthrough_activates_for_siblings():
    """The adaptive partial-agg skip: once a task observes near-zero
    reduction on a large input, sibling tasks emit per-row states.  The
    probe is deferred until the result's count is host-known (the packed
    fetch normally sets it); resolution happens at the metrics snapshot."""
    import numpy as np

    from arrow_ballista_tpu.models.schema import Field, INT64, Schema
    from arrow_ballista_tpu.ops.operators import HashAggregateExec
    from arrow_ballista_tpu.ops.physical import MemoryScanExec, TaskContext
    from arrow_ballista_tpu.models import expr as E
    import pyarrow as pa

    n = 1 << 18  # 2 partitions x 2^17 (the large-input threshold each)
    tbl = pa.table({"k": pa.array(np.arange(n), type=pa.int64()),
                    "v": pa.array(np.ones(n, dtype=np.int64))})
    scan = MemoryScanExec(Schema([Field("k", INT64), Field("v", INT64)]),
                          tbl, partitions=2)
    agg = HashAggregateExec.partial(scan, [(E.Column("k"), "k")],
                                    [("sum", E.Column("v"), "s")]) \
        if hasattr(HashAggregateExec, "partial") else None
    if agg is None:
        from arrow_ballista_tpu.ops.operators import AggSpec

        agg = HashAggregateExec(scan, [(E.Column("k"), "k")],
                                [AggSpec("sum", E.Column("v"), "s")],
                                mode="partial")
    ctx = TaskContext()
    out0 = agg.execute(0, ctx)
    # resolve the deferred probe: materialize the count, then snapshot
    for b in out0:
        b.compacted_numpy()
    agg.metrics().to_dict()
    assert getattr(agg, "_passthrough", False), \
        "all-distinct keys on a 2^17-row input must trigger passthrough"
    out1 = agg.execute(1, ctx)
    snap = agg.metrics().to_dict()
    assert snap.get("passthrough_partials", 0) >= 1
    # passthrough partials still merge correctly at the final
    from arrow_ballista_tpu.models.batch import concat_batches

    rows = sum(b.num_rows for b in out0 + out1)
    assert rows == n
