"""Row-group-granular parquet scan: partitioning + statistics pruning.

Parity: the reference's scan parallelism comes from DataFusion's ParquetExec
(file/row-group partitioning with predicate pruning); here the partition
unit is a (file, row_group) pair so a single large file scans in parallel.
"""
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.models import expr as E
from arrow_ballista_tpu.catalog import ParquetTable


@pytest.fixture(scope="module")
def sorted_parquet(tmp_path_factory):
    # x ascending across row groups => min/max stats prune range predicates
    path = str(tmp_path_factory.mktemp("rg") / "t.parquet")
    n = 10_000
    t = pa.table({
        "x": pa.array(np.arange(n, dtype=np.int64)),
        "y": pa.array(np.arange(n, dtype=np.float64) * 0.5),
        "s": pa.array(np.where(np.arange(n) < 5000, "low", "high")),
    })
    pq.write_table(t, path, row_group_size=1000)  # 10 row groups
    return path


def test_single_file_scans_in_parallel(sorted_parquet):
    t = ParquetTable("t", sorted_parquet)
    scan = t.scan(None, [], 8)
    assert scan.output_partition_count() == 8
    assert sum(len(g) for g in scan.groups) == 10
    assert scan.row_count_estimate() == 10_000


def test_row_group_pruning_range(sorted_parquet):
    t = ParquetTable("t", sorted_parquet)
    # x < 2500 keeps row groups [0..2500) => 3 of 10
    scan = t.scan(None, [E.BinOp("<", E.Column("x"), E.Lit(2500))], 8)
    assert scan.pruned_row_groups == 7
    assert scan.row_count_estimate() == 3000
    # impossible predicate prunes everything but still yields 1 empty partition
    scan = t.scan(None, [E.BinOp("<", E.Column("x"), E.Lit(-1))], 8)
    assert scan.pruned_row_groups == 10
    assert scan.output_partition_count() == 1


def test_pruning_never_changes_results(sorted_parquet):
    ctx = BallistaContext.local()
    ctx.register_parquet("t", sorted_parquet)
    out = ctx.sql("select count(*) as n, sum(x) as s from t where x < 2500").to_pandas()
    assert out.n[0] == 2500 and out.s[0] == 2500 * 2499 // 2
    out = ctx.sql("select count(*) as n from t where x >= 9995").to_pandas()
    assert out.n[0] == 5


def test_string_stats_pruning(sorted_parquet):
    t = ParquetTable("t", sorted_parquet)
    # 'high' rows only exist in row groups 5..9; string stats prune where
    # every value in a group is 'low' (min=max='low' refutes = 'high')
    scan = t.scan(None, [E.BinOp("=", E.Column("s"), E.Lit("high"))], 8)
    assert scan.pruned_row_groups == 5
    ctx = BallistaContext.local()
    ctx.register_parquet("t", sorted_parquet)
    n = ctx.sql("select count(*) as n from t where s = 'high'").to_pandas().n[0]
    assert n == 5000


def test_empty_after_pruning_query(sorted_parquet):
    ctx = BallistaContext.local()
    ctx.register_parquet("t", sorted_parquet)
    out = ctx.sql("select count(*) as n from t where x > 1000000").to_pandas()
    assert out.n[0] == 0


def test_int64_stored_decimals_match_decimal128(tmp_path):
    """The benchmark converter's int64-unscaled decimal storage (field
    metadata kind/scale) must produce identical query results to plain
    decimal128 files — including row-group stats pruning on the decimal
    column, whose integer stats are in the SCALED domain."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.config import BallistaConfig
    from benchmarks.tpch import decimal_to_int64_storage

    n = 5000
    rng = np.random.default_rng(9)
    cents = rng.integers(100, 10_000_000, n)
    import decimal as pydec

    vals = pa.array([pydec.Decimal(int(c)).scaleb(-2) for c in cents],
                    type=pa.decimal128(15, 2))
    ids = pa.array(np.arange(n), type=pa.int64())
    t128 = pa.table({"id": ids, "price": vals})
    t64 = decimal_to_int64_storage(t128)
    assert t64.schema.field("price").type == pa.int64()
    assert (t64.schema.field("price").metadata or {}).get(b"kind") == b"decimal"
    assert np.array_equal(np.asarray(t64.column("price")), cents)

    p128 = str(tmp_path / "d128.parquet")
    p64 = str(tmp_path / "d64.parquet")
    pq.write_table(t128, p128, row_group_size=1000)
    pq.write_table(t64, p64, row_group_size=1000)

    sql = ("SELECT count(*) AS c, sum(price) AS s, avg(price) AS a "
           "FROM t WHERE price > 50000.00")
    out = {}
    for tag, path in (("d128", p128), ("d64", p64)):
        ctx = BallistaContext.local(BallistaConfig({}))
        ctx.register_parquet("t", path)
        sch = ctx.catalog.provider("t").schema
        assert sch.field("price").dtype.is_decimal, tag
        assert sch.field("price").dtype.scale == 2, tag
        out[tag] = ctx.sql(sql).to_pandas()
    assert out["d128"].equals(out["d64"]), (out["d128"], out["d64"])
    # sanity: predicate actually selects a nontrivial subset
    assert 0 < int(out["d64"]["c"][0]) < n


def test_pipelined_cold_scan_matches_plain(sorted_parquet):
    """The double-buffered chunked scan (read i+1 overlapping convert+H2D of
    chunk i) must produce exactly the rows of the unpipelined path."""
    from arrow_ballista_tpu.ops.physical import TaskContext
    from arrow_ballista_tpu.utils.config import BallistaConfig
    from arrow_ballista_tpu.utils import table_cache

    table_cache.CACHE.clear()
    t = ParquetTable("t", sorted_parquet)
    scan = t.scan(None, [], 1)  # one partition holding all 10 row groups
    cfg = BallistaConfig({"ballista.batch.size": "1024",
                          "ballista.scan.cache.bytes": "0"})
    batches = scan.execute(0, TaskContext(config=cfg))
    assert len(batches) > 1  # chunking actually engaged
    xs = np.concatenate([
        np.asarray(b.columns["x"])[np.asarray(b.mask)] for b in batches])
    assert sorted(xs.tolist()) == list(range(10_000))
    # string codes decode identically across chunk-local dictionaries
    svals = []
    for b in batches:
        codes = np.asarray(b.columns["s"])[np.asarray(b.mask)]
        svals.extend(b.dicts["s"][codes].tolist())
    assert svals.count("low") == 5000 and svals.count("high") == 5000
