"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip sharding is validated without TPU pods, mirroring how the
reference tests multi-node behavior without a real cluster (SURVEY.md §4).
Env vars must be set before jax imports anywhere.
"""
import os

# The image's site hook registers an experimental TPU PJRT plugin ("axon")
# in every python process when PALLAS_AXON_POOL_IPS is set; its tunnel can
# hang for minutes.  Blank it so tests never touch the TPU path.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"  # force: tests always run on the CPU mesh
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# The image pre-imports jax with the TPU platform via a site hook, so the
# env vars above can be too late; config.update before first backend use
# still wins (XLA reads XLA_FLAGS when the CPU client is created).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import faulthandler  # noqa: E402
import sys  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Runtime lock-order validation (BALLISTA_LOCK_ORDER_RUNTIME=1): patch the
# threading lock constructors NOW — conftest imports before any test module,
# so package classes created during the run get recording proxies.  The
# observed acquisition graph is checked against the static model at session
# end (see pytest_sessionfinish below).  Zero-cost when the env var is off.
from arrow_ballista_tpu.analysis import lock_order as _lock_order  # noqa: E402

_LOCK_ORDER_ON = bool(_lock_order.enabled())
if _LOCK_ORDER_ON:
    _lock_order.install()

# Suite-level watchdog (round-2 failure mode: one deadlocked test hung the
# whole suite forever).  Each test re-arms a hard deadline; on expiry every
# thread's stack is dumped and the process exits non-zero, so a hang can
# never silently eat a run.  pytest-timeout is not in the image, hence
# faulthandler.
TEST_TIMEOUT_S = int(os.environ.get("BALLISTA_TEST_TIMEOUT", "600"))


def pytest_sessionfinish(session, exitstatus):
    if not _LOCK_ORDER_ON:
        return
    rep = _lock_order.validate(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    print("\n" + rep.details(), file=sys.__stderr__)
    if not rep.ok:
        # a disagreement between the static lock-order model and the run's
        # observed acquisitions must fail CI even when every test passed
        session.exitstatus = 3


def pytest_configure(config):
    # no pytest.ini in this repo: markers are registered here so
    # --strict-markers stays usable and `-m chaos` selects the fault
    # -injection recovery suite (tests/test_chaos.py)
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection recovery tests")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 runs")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    if TEST_TIMEOUT_S > 0:
        # sys.__stderr__: pytest's fd capture redirects fd 2 to an unlinked
        # temp file, so dumping there would lose the stacks
        faulthandler.dump_traceback_later(TEST_TIMEOUT_S, exit=True,
                                          file=sys.__stderr__)
    yield
    if TEST_TIMEOUT_S > 0:
        faulthandler.cancel_dump_traceback_later()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
