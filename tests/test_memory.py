"""Memory-pressure robustness plane tests (arrow_ballista_tpu/memory/).

Covers the contract from the memory subsystem:

- governor reserve/grant/release accounting over the host/device pools,
  budget 0 = unlimited, ``try_reserve`` denial -> spill path (or re-raise
  with spill disabled), ``force_reserve`` over-budget grants counted;
- the ``executor.memory.reserve`` failpoint denies/delays grants so chaos
  plans can force the spill path on an unconstrained executor;
- spill runs: Arrow IPC write/read round trip, CRC verification turning
  silent disk corruption into a retryable :class:`IntegrityError`;
- concurrent reservations never oversubscribe a budgeted pool and never
  leak (final reserved == 0);
- spilled grouped aggregation and hash joins are BIT-IDENTICAL to their
  in-memory execution (the tentpole claim), via a tiny host budget that
  denies every materialization;
- executor pressure degrades scheduler offers and feeds admission
  shedding (retriable, never a quarantine strike).
"""
import threading

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu import Field, INT64, Schema, faults
from arrow_ballista_tpu.memory import MemoryGovernor, Reservation, STATS
from arrow_ballista_tpu.memory.spill import Spiller
from arrow_ballista_tpu.utils.config import (
    MEM_HOST_BUDGET,
    MEM_SPILL_ENABLED,
    BallistaConfig,
)
from arrow_ballista_tpu.utils.errors import IntegrityError, MemoryExhausted


@pytest.fixture(autouse=True)
def _fresh_stats():
    """Process-global memory STATS must not leak between tests (or into
    the rest of the suite)."""
    STATS.reset()
    faults.clear()
    yield
    STATS.reset()
    faults.clear()


# --------------------------------------------------------------------------
# governor accounting units
# --------------------------------------------------------------------------

def test_unlimited_budget_always_grants_and_accounts():
    gov = MemoryGovernor()  # budget 0 = unlimited
    assert gov.available("host") is None
    r = gov.reserve(1 << 30, site="unit")
    assert gov.reserved("host") == 1 << 30
    assert STATS.snapshot()["reserved_bytes.host"] == 1 << 30
    assert gov.pressure() == 0.0, "unbudgeted pools exert no pressure"
    r.release()
    assert gov.reserved("host") == 0
    r.release()  # idempotent
    assert gov.reserved("host") == 0
    assert STATS.snapshot()["reserved_bytes.host"] == 0


def test_budgeted_reserve_denial_and_pressure():
    gov = MemoryGovernor(host_budget=1000)
    a = gov.reserve(600, site="op-a")
    assert gov.available("host") == 400
    assert gov.pressure() == pytest.approx(0.6)
    with pytest.raises(MemoryExhausted):
        gov.reserve(500, site="op-b")
    assert gov.reserved("host") == 600, "denied reservation must not leak"
    b = gov.reserve(400, site="op-b")
    assert gov.pressure() == pytest.approx(1.0)
    a.release()
    b.release()
    assert gov.pressure() == 0.0


def test_try_reserve_denial_is_the_spill_signal():
    gov = MemoryGovernor(host_budget=100)
    assert isinstance(gov.try_reserve(100), Reservation)
    denied = gov.try_reserve(1)
    assert denied is None, "None tells the operator to take its spill path"
    assert STATS.snapshot()["reserve_denied_total"] == 1


def test_try_reserve_reraises_with_spill_disabled():
    gov = MemoryGovernor(host_budget=100, spill_enabled=False)
    gov.reserve(100)
    with pytest.raises(MemoryExhausted) as exc:
        gov.try_reserve(50, site="agg-state")
    assert exc.value.retryable, \
        "a denial that cannot degrade to spill must stay retryable"
    assert STATS.snapshot()["reserve_denied_total"] == 1


def test_force_reserve_overshoots_and_counts():
    gov = MemoryGovernor(host_budget=100)
    r = gov.force_reserve(250, site="left-outer-build")
    assert gov.reserved("host") == 250
    assert gov.pressure() == pytest.approx(2.5), \
        "the overshoot must be visible in the pressure signal"
    assert STATS.snapshot()["over_budget_grants_total"] == 1
    r.release()
    # within budget: no over-budget count
    gov.force_reserve(10).release()
    assert STATS.snapshot()["over_budget_grants_total"] == 1


def test_reservation_context_manager_unwinds():
    gov = MemoryGovernor(host_budget=100)
    with pytest.raises(RuntimeError):
        with gov.reserve(80):
            assert gov.reserved("host") == 80
            raise RuntimeError("operator blew up")
    assert gov.reserved("host") == 0


def test_from_config_budgets_and_spill_knob():
    gov = MemoryGovernor.from_config(BallistaConfig({
        MEM_HOST_BUDGET: "4096", MEM_SPILL_ENABLED: "false"}))
    assert gov.budget("host") == 4096
    assert gov.budget("device") == 0
    assert gov.spill_enabled is False
    auto = MemoryGovernor.from_config(BallistaConfig({MEM_HOST_BUDGET: "auto"}))
    assert auto.budget("host") > (1 << 30), "'auto' resolves a real budget"


# --------------------------------------------------------------------------
# executor.memory.reserve failpoint
# --------------------------------------------------------------------------

def test_reserve_failpoint_denies_an_unlimited_pool():
    """Chaos plans force the spill path without configuring any budget:
    error=resource at the failpoint IS a governor denial."""
    gov = MemoryGovernor()  # unlimited
    plan = faults.FaultPlan.from_obj({"seed": 5, "rules": [{
        "site": "executor.memory.reserve", "action": "raise",
        "error": "resource", "times": 1}]})
    with faults.use_plan(plan):
        assert gov.try_reserve(1024, site="agg-state") is None
        assert gov.try_reserve(1024, site="agg-state") is not None
    assert plan.schedule() == (("executor.memory.reserve", 0, 1, "raise"),)
    assert STATS.snapshot()["reserve_denied_total"] == 1
    assert gov.reserved("host") == 1024, \
        "the denied attempt must not have reserved anything"


def test_reserve_failpoint_match_filters_on_op():
    plan = faults.FaultPlan.from_obj({"seed": 5, "rules": [{
        "site": "executor.memory.reserve", "action": "raise",
        "error": "resource", "times": -1, "match": {"op": "join-build"}}]})
    gov = MemoryGovernor()
    with faults.use_plan(plan):
        assert gov.try_reserve(10, site="agg-state") is not None
        assert gov.try_reserve(10, site="join-build") is None


# --------------------------------------------------------------------------
# concurrent reservations: no oversubscription, no leaks
# --------------------------------------------------------------------------

def test_concurrent_reservations_race():
    budget = 10_000
    gov = MemoryGovernor(host_budget=budget)
    errors = []
    granted = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        for _ in range(200):
            n = int(rng.integers(1, 4000))
            r = gov.try_reserve(n, site=f"w{seed}")
            if r is None:
                continue
            held = gov.reserved("host")
            if held > budget:
                errors.append(f"oversubscribed: {held} > {budget}")
            granted.append(n)
            r.release()

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    assert granted, "some reservations must have been granted"
    assert gov.reserved("host") == 0, "every grant must release"
    assert STATS.snapshot()["reserved_bytes.host"] == 0


# --------------------------------------------------------------------------
# spill runs: IPC round trip + CRC integrity
# --------------------------------------------------------------------------

def _spill_schema():
    return Schema([Field("g", INT64), Field("v", INT64)])


def test_spiller_round_trip(tmp_path):
    sp = Spiller(str(tmp_path), job_id="j1", tag="agg")
    schema = _spill_schema()
    sp.write_run(schema, {"g": np.array([1, 2], dtype=np.int64),
                          "v": np.array([10, 20], dtype=np.int64)}, {})
    sp.write_run(schema, {"g": np.array([3], dtype=np.int64),
                          "v": np.array([30], dtype=np.int64)}, {})
    batches = sp.read(schema)
    got = pd.concat([b.to_pandas() for b in batches], ignore_index=True)
    pd.testing.assert_frame_equal(
        got, pd.DataFrame({"g": [1, 2, 3], "v": [10, 20, 30]}),
        check_dtype=False)
    snap = STATS.snapshot()
    assert snap["spill_runs_total"] == 2
    assert snap["spill_bytes_total"] > 0
    sp.cleanup()
    assert sp.runs == []


def test_spill_corruption_detected_on_read(tmp_path):
    sp = Spiller(str(tmp_path), job_id="j1", tag="agg")
    schema = _spill_schema()
    run = sp.write_run(schema, {"g": np.arange(100, dtype=np.int64),
                                "v": np.arange(100, dtype=np.int64)}, {})
    with open(run.path, "r+b") as fh:  # silent bit rot after the CRC
        fh.seek(32)
        fh.write(b"\xff")
    with pytest.raises(IntegrityError) as exc:
        sp.read(schema)
    assert exc.value.retryable, \
        "spill corruption is lineage-recoverable, so it must be retryable"


def test_spill_write_failpoint_corrupts_after_crc(tmp_path):
    plan = faults.FaultPlan.from_obj({"seed": 3, "rules": [{
        "site": "executor.spill.write", "action": "corrupt", "times": 1}]})
    sp = Spiller(str(tmp_path), job_id="j1", tag="agg")
    schema = _spill_schema()
    with faults.use_plan(plan):
        sp.write_run(schema, {"g": np.arange(50, dtype=np.int64),
                              "v": np.arange(50, dtype=np.int64)}, {})
    assert plan.schedule() == (("executor.spill.write", 0, 1, "corrupt"),)
    with pytest.raises(IntegrityError):
        sp.read(schema)


# --------------------------------------------------------------------------
# spilled execution is bit-identical to in-memory (the tentpole claim)
# --------------------------------------------------------------------------

QUERIES = (
    # grouped aggregation: sum/count/min/max state spills per input batch
    "select g, sum(v) as s, count(*) as n, min(v) as lo, max(v) as hi "
    "from t group by g order by g",
    # hash join: the build side spills as hash-range partitions
    "select t.g, sum(t.v + d.w) as s from t join d on t.g = d.g "
    "group by t.g order by t.g",
    # semi/anti shapes ride the probe-mask merge path
    "select count(*) as n from t where g in (select g from d where w > 50)",
    "select count(*) as n from t where g not in (select g from d)",
)


def _memory_ctx(budget=None):
    from arrow_ballista_tpu.client.context import BallistaContext

    conf = {"ballista.shuffle.partitions": "4"}
    if budget is not None:
        conf[MEM_HOST_BUDGET] = str(budget)
    c = BallistaContext.local(BallistaConfig(conf))
    rng = np.random.default_rng(23)
    c.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 40, 6000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 1000, 6000).astype(np.int64)),
    }))
    c.register_table("d", pa.table({
        "g": pa.array(np.arange(0, 25, dtype=np.int64)),
        "w": pa.array(rng.integers(0, 100, 25).astype(np.int64)),
    }))
    return c


def test_forced_spill_results_bit_identical():
    base_ctx = _memory_ctx()
    base = [base_ctx.sql(q).to_pandas() for q in QUERIES]
    assert STATS.snapshot().get("spill_runs_total", 0) == 0, \
        "the unlimited baseline must not spill"

    STATS.reset()
    tiny_ctx = _memory_ctx(budget=2048)  # denies every materialization
    got = [tiny_ctx.sql(q).to_pandas() for q in QUERIES]
    snap = STATS.snapshot()
    assert snap["reserve_denied_total"] > 0
    assert snap["spill_runs_total"] > 0, "the tiny budget must force spill"
    assert snap["reserved_bytes.host"] == 0, "no reservation leaks"
    for q, b, g in zip(QUERIES, base, got):
        pd.testing.assert_frame_equal(b.reset_index(drop=True),
                                      g.reset_index(drop=True))


def test_spill_disabled_denial_raises_retryable():
    from arrow_ballista_tpu.client.context import BallistaContext

    c = BallistaContext.local(BallistaConfig({
        "ballista.shuffle.partitions": "2",
        MEM_HOST_BUDGET: "1024", MEM_SPILL_ENABLED: "false"}))
    rng = np.random.default_rng(7)
    c.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 10, 4000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 4000).astype(np.int64)),
    }))
    with pytest.raises(MemoryExhausted):
        c.sql("select g, sum(v) as s from t group by g order by g").to_pandas()


# --------------------------------------------------------------------------
# pressure-aware offers + admission shed
# --------------------------------------------------------------------------

def test_offers_prefer_low_pressure_executors():
    from arrow_ballista_tpu.scheduler.cluster import ClusterState
    from arrow_ballista_tpu.scheduler.types import (
        ExecutorHeartbeat,
        ExecutorMetadata,
    )

    cs = ClusterState()
    for eid, pressure in (("hot", 0.95), ("calm", 0.1)):
        cs.register_executor(ExecutorMetadata(eid, task_slots=4))
        cs.save_heartbeat(ExecutorHeartbeat(eid, memory_pressure=pressure))
    got = cs.reserve_slots(2)
    assert got and all(r.executor_id == "calm" for r in got), \
        f"offers must land on the low-pressure executor first: {got}"
    assert cs.min_alive_pressure() == pytest.approx(0.1)
    cs.save_heartbeat(ExecutorHeartbeat("calm", memory_pressure=0.97))
    assert cs.min_alive_pressure() == pytest.approx(0.95), \
        "the fleet floor rises only when EVERY executor is saturated"


def test_admission_memory_shed_retriable():
    from arrow_ballista_tpu.admission import AdmissionController

    pressure = [0.99]
    failures = []
    admitted = []

    def make(threshold=0.95):
        return AdmissionController(
            admit_cb=lambda job_id, plan_fn: admitted.append(job_id),
            fail_cb=lambda job_id, msg: failures.append((job_id, msg)),
            pending_tasks_fn=lambda: 0,
            total_slots_fn=lambda: 8,
            memory_pressure_fn=lambda: pressure[0],
            memory_shed_threshold=threshold)

    ctl = make()
    ctl.submit("j-shed", lambda: None)
    assert not admitted
    assert failures and failures[0][0] == "j-shed"
    assert "memory saturated" in failures[0][1]
    assert "retry after" in failures[0][1]
    assert ctl.snapshot()["memory_shed_total"] == 1
    # pressure drops below the threshold: jobs admit normally again
    pressure[0] = 0.2
    make().submit("j-ok", lambda: None)
    assert admitted == ["j-ok"]
    # threshold <= 0 disables the feed entirely
    pressure[0] = 1.0
    make(threshold=0.0).submit("j-off", lambda: None)
    assert admitted == ["j-ok", "j-off"]


# --------------------------------------------------------------------------
# bugfix regression: governor denial never takes a quarantine strike
# --------------------------------------------------------------------------

def test_resource_exhausted_takes_no_quarantine_strike():
    """Two RESOURCE_EXHAUSTED failures back to back would quarantine the
    executor if they counted as strikes (threshold default 3, but any
    strike is wrong: the executor protected itself from OOM).  They must
    neither strike NOR clear an existing IO_ERROR streak."""
    from arrow_ballista_tpu.scheduler.types import (
        FailedReason,
        IO_ERROR,
        RESOURCE_EXHAUSTED,
        TaskId,
        TaskStatus,
    )
    from tests.test_scheduler import scheduler_test

    server, _launcher = scheduler_test(n_executors=1)
    try:
        def failed(kind, attempt):
            return TaskStatus(
                TaskId("job-m", 1, 0, task_attempt=attempt), "exec-0",
                "failed", failure=FailedReason(kind, "m"))

        for attempt in range(5):
            server._record_quarantine_signals(
                "exec-0", [failed(RESOURCE_EXHAUSTED, attempt)])
        assert server.quarantine.count() == 0, \
            "memory back-pressure must never quarantine an executor"
        # and it must not RESET a real failure streak either: two genuine
        # IO errors with a shed in between still quarantine at threshold 2
        server.quarantine.threshold = 2
        server._record_quarantine_signals("exec-0", [failed(IO_ERROR, 10)])
        server._record_quarantine_signals(
            "exec-0", [failed(RESOURCE_EXHAUSTED, 11)])
        server._record_quarantine_signals("exec-0", [failed(IO_ERROR, 12)])
        assert server.quarantine.count() == 1, \
            "a shed between two IO strikes must not have reset the streak"
    finally:
        server.shutdown()


def test_resource_exhausted_taxonomy():
    """RESOURCE_EXHAUSTED is retryable (the scheduler re-runs the task,
    ideally elsewhere) AND bounds retries (count_to_failures, so a
    saturated cluster cannot loop a task forever) — while staying exempt
    from quarantine strikes (previous test)."""
    from arrow_ballista_tpu.scheduler.types import (
        FailedReason,
        RESOURCE_EXHAUSTED,
    )

    reason = FailedReason(RESOURCE_EXHAUSTED, "governor denied")
    assert reason.retryable
    assert reason.count_to_failures
    assert MemoryExhausted("host", 10, 0, "agg").retryable
    assert IntegrityError("executor.spill.read", "crc", path="x").retryable
