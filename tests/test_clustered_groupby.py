"""Clustered group-by early-HAVING rewrite (q18's subquery shape).

When parquet stats prove the scan is clustered on the single group key,
partial aggregates over contiguous partitions are final for all keys
outside neighbor-overlap windows, so the HAVING predicate applies
in-task and the exchange ships ~nothing (physical_planner.py
_clustered_having_pushdown).
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


def _write_clustered(path, n_keys=5000, seed=3):
    rng = np.random.default_rng(seed)
    # 1-7 rows per key, rows sorted by key (lineitem-like clustering);
    # small row groups so keys straddle row-group boundaries
    reps = rng.integers(1, 8, n_keys)
    keys = np.repeat(np.arange(n_keys, dtype=np.int64), reps)
    qty = rng.integers(1, 50, len(keys)).astype(np.int64)
    pq.write_table(pa.table({"k": keys, "q": qty}), path,
                   row_group_size=1000)
    return pd.DataFrame({"k": keys, "q": qty})


SQL = ("select k, sum(q) as sq from t group by k "
       "having sum(q) > 150 order by k")


def _oracle(df):
    g = df.groupby("k").q.sum()
    g = g[g > 150]
    return g


@pytest.mark.parametrize("partitions", ["4", "auto"])
def test_clustered_having_matches_oracle(tmp_path, partitions):
    path = str(tmp_path / "t.parquet")
    df = _write_clustered(path)
    ctx = BallistaContext.standalone(
        BallistaConfig({"ballista.shuffle.partitions": partitions}),
        concurrent_tasks=2)
    ctx.register_parquet("t", path)
    out = ctx.sql(SQL).to_pandas()
    ora = _oracle(df)
    assert out.k.tolist() == ora.index.tolist()
    assert out.sq.tolist() == ora.values.tolist()
    # the rewrite actually engaged: the partial-agg stage's shuffle wrote
    # only survivors + window keys, not every state
    sched = ctx._standalone.scheduler
    graph = sched.jobs.get_graph(list(sched.jobs._status)[-1])
    wrote = []
    early = 0
    for st in graph.stages.values():
        m = st.aggregate_metrics()
        ef = sum(v for k, v in m.items()
                 if k.endswith("clustered_early_filters"))
        early += ef
        if ef:
            wrote.append(sum(v for k, v in m.items()
                             if k.endswith("ShuffleWriterExec.output_rows")))
    if partitions == "4":  # auto collapses this small table to 1 partition
        assert early > 0, "rewrite did not engage"
        survivors = len(_oracle(df))
        assert wrote and sum(wrote) < survivors + 200  # vs ~5000 states
    ctx.shutdown()


def test_unclustered_data_bails_and_stays_correct(tmp_path):
    path = str(tmp_path / "t.parquet")
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 5000, 20_000).astype(np.int64)  # NOT sorted
    qty = rng.integers(1, 50, len(keys)).astype(np.int64)
    pq.write_table(pa.table({"k": keys, "q": qty}), path, row_group_size=1000)
    df = pd.DataFrame({"k": keys, "q": qty})
    ctx = BallistaContext.standalone(
        BallistaConfig({"ballista.shuffle.partitions": "4"}),
        concurrent_tasks=2)
    ctx.register_parquet("t", path)
    out = ctx.sql(SQL).to_pandas()
    ora = _oracle(df)
    assert out.k.tolist() == ora.index.tolist()
    assert out.sq.tolist() == ora.values.tolist()
    sched = ctx._standalone.scheduler
    graph = sched.jobs.get_graph(list(sched.jobs._status)[-1])
    early = sum(v for st in graph.stages.values()
                for k, v in st.aggregate_metrics().items()
                if k.endswith("clustered_early_filters"))
    assert early == 0  # unclustered: the rule must bail
    ctx.shutdown()


def test_serde_round_trips_annotation(tmp_path):
    from arrow_ballista_tpu import serde
    from arrow_ballista_tpu.catalog import SchemaCatalog, ParquetTable
    from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner
    from arrow_ballista_tpu.sql.optimizer import optimize
    from arrow_ballista_tpu.sql.planner import SqlToRel
    from arrow_ballista_tpu.sql.parser import parse_sql
    from arrow_ballista_tpu.ops import operators as O

    path = str(tmp_path / "t.parquet")
    _write_clustered(path)
    cat = SchemaCatalog()
    cat.register(ParquetTable("t", path))
    planned = PhysicalPlanner(cat, BallistaConfig(
        {"ballista.shuffle.partitions": "4"})).plan_query(
        optimize(SqlToRel(cat).plan(parse_sql(SQL))))

    def find_clustered(p):
        if isinstance(p, O.HashAggregateExec) \
                and getattr(p, "clustered", None) is not None:
            return p
        for c in p.children():
            got = find_clustered(c)
            if got is not None:
                return got
        return None

    agg = find_clustered(planned.plan)
    assert agg is not None, "rewrite did not annotate the plan"
    rt = serde.plan_from_obj(serde.plan_to_obj(planned.plan))
    agg2 = find_clustered(rt)
    assert agg2 is not None
    assert agg2.clustered[1] == agg.clustered[1]
    # the contiguous regrouping survives serde too
    from arrow_ballista_tpu.ops.physical import ParquetScanExec

    def find_scan(p):
        if isinstance(p, ParquetScanExec):
            return p
        for c in p.children():
            got = find_scan(c)
            if got is not None:
                return got
        return None

    assert find_scan(rt).groups == find_scan(planned.plan).groups


def test_within_rowgroup_disorder_falls_back(tmp_path):
    """Row-group stats can prove range disjointness while rows INSIDE a
    group are unordered; the presorted grouping detects the disorder at
    runtime and re-runs the sorted path — results stay exact."""
    rng = np.random.default_rng(11)
    parts = []
    for lo in range(0, 5000, 1000):
        block = np.repeat(np.arange(lo, lo + 1000, dtype=np.int64),
                          rng.integers(1, 4, 1000))
        rng.shuffle(block)  # disjoint rg ranges, unsorted inside
        parts.append(block)
    keys = np.concatenate(parts)
    qty = rng.integers(1, 50, len(keys)).astype(np.int64)
    path = str(tmp_path / "t.parquet")
    writer = pq.ParquetWriter(path, pa.schema([("k", pa.int64()),
                                               ("q", pa.int64())]))
    off = 0
    for block in parts:
        n = len(block)
        writer.write_table(pa.table({"k": keys[off:off+n],
                                     "q": qty[off:off+n]}))
        off += n
    writer.close()
    df = pd.DataFrame({"k": keys, "q": qty})
    ctx = BallistaContext.standalone(
        BallistaConfig({"ballista.shuffle.partitions": "4"}),
        concurrent_tasks=2)
    ctx.register_parquet("t", path)
    out = ctx.sql(SQL).to_pandas()
    ora = _oracle(df)
    assert out.k.tolist() == ora.index.tolist()
    assert out.sq.tolist() == ora.values.tolist()
    sched = ctx._standalone.scheduler
    graph = sched.jobs.get_graph(list(sched.jobs._status)[-1])
    metrics = {k: v for st in graph.stages.values()
               for k, v in st.aggregate_metrics().items()}
    assert any(k.endswith("presort_fallbacks") and v > 0
               for k, v in metrics.items()), metrics
    ctx.shutdown()


def test_null_keys_never_early_filtered(tmp_path):
    """NULL keys ride an in-band sentinel that parquet stats exclude, so
    NULL-group partials can split across partitions; the rewrite must ship
    them through the exchange (sentinel interval), never treat a partial
    NULL-group state as final."""
    rng = np.random.default_rng(17)
    keys = np.repeat(np.arange(4000, dtype=np.float64),
                     rng.integers(1, 4, 4000))
    # scatter NULLs throughout: each partition's null partial-sum stays
    # under the HAVING threshold while the merged sum passes it
    null_pos = np.arange(50, len(keys), len(keys) // 16)
    keys[null_pos] = np.nan
    qty = np.full(len(keys), 1, dtype=np.int64)
    qty[null_pos] = 40  # 16 nulls x 40 = 640 total, ~160/partition
    pa_keys = pa.array([None if np.isnan(v) else int(v) for v in keys],
                       type=pa.int64())
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": pa_keys, "q": pa.array(qty)}), path,
                   row_group_size=1000)
    ctx = BallistaContext.standalone(
        BallistaConfig({"ballista.shuffle.partitions": "4"}),
        concurrent_tasks=2)
    ctx.register_parquet("t", path)
    out = ctx.sql("select k, sum(q) as sq from t group by k "
                  "having sum(q) > 300 order by k").to_pandas()
    # only the NULL group passes the threshold
    assert len(out) == 1
    assert np.isnan(out.k.iloc[0])
    assert out.sq.iloc[0] == 16 * 40
    ctx.shutdown()


def _find_op(plan, pred):
    if pred(plan):
        return plan
    for c in plan.children():
        got = _find_op(c, pred)
        if got is not None:
            return got
    return None


def _plan(path, partitions="4"):
    from arrow_ballista_tpu.catalog import ParquetTable, SchemaCatalog
    from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner
    from arrow_ballista_tpu.sql.optimizer import optimize
    from arrow_ballista_tpu.sql.parser import parse_sql
    from arrow_ballista_tpu.sql.planner import SqlToRel

    cat = SchemaCatalog()
    cat.register(ParquetTable("t", path))
    cfg = BallistaConfig({"ballista.shuffle.partitions": partitions})
    planned = PhysicalPlanner(cat, cfg).plan_query(
        optimize(SqlToRel(cat).plan(parse_sql(SQL))))
    return planned, cfg


def test_single_range_probe_rejected_without_partition_collapse(tmp_path):
    """A probe whose contiguous regroup collapses to ONE range (a huge
    trailing row group absorbs the whole regroup) is rejected by the
    planner — and, being side-effect free, must leave the scan's original
    partitioning untouched instead of serializing the whole scan."""
    from arrow_ballista_tpu.catalog import ParquetTable
    from arrow_ballista_tpu.ops import operators as O
    from arrow_ballista_tpu.ops.physical import ParquetScanExec

    rng = np.random.default_rng(3)
    reps = rng.integers(1, 8, 2000)
    keys = np.repeat(np.arange(2000, dtype=np.int64), reps)
    qty = rng.integers(1, 50, len(keys)).astype(np.int64)
    table = pa.table({"k": keys, "q": qty})
    path = str(tmp_path / "t.parquet")
    writer = pq.ParquetWriter(path, table.schema)
    writer.write_table(table.slice(0, 10))      # tiny row group ...
    writer.write_table(table.slice(10))         # ... then one huge one
    writer.close()

    scan = ParquetTable("t", path).scan(None, [], 2)
    before = [list(g) for g in scan.groups]
    assert len(before) == 2
    probe = scan.clustered_ranges("k")
    assert probe is not None
    groups, ranges = probe
    assert len(ranges) == 1, "regroup should collapse to one range here"
    assert [list(g) for g in scan.groups] == before, \
        "probe must not mutate the scan's partitioning"

    # planner end-to-end: annotation rejected, scan parallelism preserved
    planned, _cfg = _plan(path, partitions="2")
    agg = _find_op(planned.plan,
                   lambda p: isinstance(p, O.HashAggregateExec)
                   and getattr(p, "clustered", None) is not None)
    assert agg is None, "single-range annotation must be rejected"
    scan_op = _find_op(planned.plan,
                       lambda p: isinstance(p, ParquetScanExec))
    assert len(scan_op.groups) == 2, "rejected probe collapsed the scan"

    # and the query is still correct
    df = pd.DataFrame({"k": keys, "q": qty})
    ctx = BallistaContext.standalone(
        BallistaConfig({"ballista.shuffle.partitions": "2"}),
        concurrent_tasks=2)
    ctx.register_parquet("t", path)
    out = ctx.sql(SQL).to_pandas()
    ora = _oracle(df)
    assert out.k.tolist() == ora.index.tolist()
    assert out.sq.tolist() == ora.values.tolist()
    ctx.shutdown()


def test_stale_declared_ranges_disable_early_filter(tmp_path):
    """Stale parquet stats guard: when a partition's observed key min/max
    leaves the range the annotation declared (file rewritten after
    planning), the runtime check must drop the early HAVING filter —
    trusting stale overlap windows would silently drop boundary groups."""
    from arrow_ballista_tpu.ops import operators as O
    from arrow_ballista_tpu.scheduler.standalone import StandaloneCluster

    path = str(tmp_path / "t.parquet")
    df = _write_clustered(path)
    planned, cfg = _plan(path)
    agg = _find_op(planned.plan,
                   lambda p: isinstance(p, O.HashAggregateExec)
                   and getattr(p, "clustered", None) is not None)
    assert agg is not None, "rewrite did not annotate the plan"
    pred, _intervals, ranges = agg.clustered
    # simulate a post-planning rewrite: declared ranges (and the overlap
    # windows derived from them) no longer describe the file's keys
    shifted = [(lo + 10_000_000, hi + 10_000_000) for lo, hi in ranges]
    agg.clustered = (pred, [], shifted)

    cluster = StandaloneCluster(cfg, concurrent_tasks=2)
    try:
        batches = cluster.execute(planned)
        out = pd.concat([b.to_pandas() for b in batches],
                        ignore_index=True).sort_values("k")
        ora = _oracle(df)
        assert out.k.tolist() == ora.index.tolist()
        assert out.sq.tolist() == ora.values.tolist()
        graph = cluster.scheduler.jobs.get_graph(
            list(cluster.scheduler.jobs._status)[-1])
        metrics = {k: v for st in graph.stages.values()
                   for k, v in st.aggregate_metrics().items()}
        assert any(k.endswith("clustered_range_mismatches") and v > 0
                   for k, v in metrics.items()), metrics
        assert sum(v for k, v in metrics.items()
                   if k.endswith("clustered_early_filters")) == 0, \
            "stale ranges must disable the early filter"
    finally:
        cluster.shutdown()
