import numpy as np
import pytest

from arrow_ballista_tpu import (
    BallistaConfig,
    ColumnBatch,
    Field,
    INT64,
    STRING,
    Schema,
    concat_batches,
    decimal,
)
from arrow_ballista_tpu.utils.errors import ConfigurationError


def make_batch():
    schema = Schema([
        Field("k", INT64),
        Field("price", decimal(2)),
        Field("flag", STRING),
    ])
    data = {
        "k": np.array([1, 2, 3], dtype=np.int64),
        "price": np.array([1050, 2099, 399], dtype=np.int64),  # $10.50, $20.99, $3.99
        "flag": np.array([0, 1, 0], dtype=np.int32),
    }
    return ColumnBatch.from_numpy(schema, data, dicts={"flag": np.array(["A", "N"], dtype=object)})


def test_batch_roundtrip_pandas():
    b = make_batch()
    assert b.num_rows == 3
    assert b.capacity >= 3
    df = b.to_pandas()
    assert list(df["k"]) == [1, 2, 3]
    assert list(df["flag"]) == ["A", "N", "A"]
    np.testing.assert_allclose(df["price"], [10.50, 20.99, 3.99])


def test_batch_to_arrow():
    t = make_batch().to_arrow()
    assert t.num_rows == 3
    assert t.column("flag").to_pylist() == ["A", "N", "A"]


def test_concat_batches():
    b = make_batch()
    out = concat_batches(b.schema, [b, b])
    assert out.num_rows == 6
    df = out.to_pandas()
    assert list(df["k"]) == [1, 2, 3, 1, 2, 3]


def test_int64_preserved_through_device():
    # x64 must be on: decimals are int64 fixed-point.
    b = make_batch()
    assert str(b.columns["price"].dtype) == "int64"


def test_config_validation():
    cfg = BallistaConfig.builder().set("ballista.shuffle.partitions", "8").build()
    assert cfg.shuffle_partitions == 8
    assert cfg.batch_size == 1 << 17
    with pytest.raises(ConfigurationError):
        BallistaConfig({"ballista.bogus": 1})
    with pytest.raises(ConfigurationError):
        BallistaConfig({"ballista.shuffle.partitions": "abc"})


def test_wire_narrowing_mixed_width_files(tmp_path):
    """Two shuffle files for one partition — one int32-narrowed, one kept
    int64 (values out of range) — read back as one int64 batch."""
    import numpy as np

    from arrow_ballista_tpu.models.ipc import read_ipc_files, write_ipc_rows
    from arrow_ballista_tpu.models.schema import Field, INT64, Schema

    sch = Schema([Field("v", INT64)])
    small = {"v": np.arange(100, dtype=np.int64)}
    big = {"v": np.arange(100, dtype=np.int64) + 2**40}
    p1, p2 = str(tmp_path / "a.arrow"), str(tmp_path / "b.arrow")
    write_ipc_rows(sch, small, {}, p1)
    write_ipc_rows(sch, big, {}, p2)

    import pyarrow as pa
    import pyarrow.ipc as ipc

    assert ipc.open_file(pa.memory_map(p1)).schema.field("v").type == pa.int32()
    assert ipc.open_file(pa.memory_map(p2)).schema.field("v").type == pa.int64()

    batches = read_ipc_files([p1, p2], sch)
    vals = np.concatenate([b.compacted_numpy()["v"] for b in batches])
    assert vals.dtype == np.int64
    assert sorted(vals) == sorted(list(small["v"]) + list(big["v"]))


def test_packed_numpy_round_trip_all_dtypes():
    """packed_numpy: ONE device fetch carrying compacted columns + count
    (kernels.pack_for_host layout: int64 buffer + f64 side stack), exact
    across every physical dtype, with the too-small-hint refetch ladder."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from arrow_ballista_tpu.models.batch import ColumnBatch
    from arrow_ballista_tpu.models.schema import DataType, Field, Schema

    sch = Schema([
        Field("a", DataType("int64")), Field("b", DataType("float64")),
        Field("c", DataType("int32")), Field("d", DataType("date32")),
        Field("e", DataType("decimal", 2)), Field("f", DataType("bool")),
        Field("g", DataType("float32")), Field("s", DataType("string")),
    ])
    n = 777
    rng = np.random.default_rng(3)
    data = {
        "a": np.arange(n) * 3, "b": rng.random(n),
        "c": np.arange(n, dtype=np.int32) - 5,
        "d": np.arange(n, dtype=np.int32), "e": np.arange(n) * 100 + 7,
        "f": np.arange(n) % 3 == 0, "g": rng.random(n).astype(np.float32),
        "s": (np.arange(n) % 4).astype(np.int32),
    }
    dicts = {"s": np.array(["w", "x", "y", "z"], dtype=object)}
    b0 = ColumnBatch.from_numpy(sch, data, dicts=dicts)
    mask = np.asarray(b0.mask).copy()
    mask[::7] = False  # knock out rows; count becomes device-only
    live = np.nonzero(mask)[0]

    b = ColumnBatch(sch, b0.columns, jax.device_put(mask), b0.dicts)
    out, cnt = b.packed_numpy()
    assert cnt == len(live) and b._num_rows == cnt  # count rode the buffer
    for k in data:
        exp = np.asarray(data[k])[live[live < n]]
        assert out[k].dtype == sch.field(k).dtype.np_dtype, k
        assert np.array_equal(out[k], exp), k

    # synthetic extra int32 column (shuffle bucket ids) packs alongside
    out2, _ = ColumnBatch(sch, b0.columns, jax.device_put(mask), b0.dicts) \
        .packed_numpy(extra32={"__bucket__": jnp.arange(b.capacity,
                                                        dtype=jnp.int32) % 5})
    assert np.array_equal(out2["__bucket__"], live.astype(np.int32) % 5)

    # a hint below the real count triggers exactly one exact-size refetch
    out3, cnt3 = ColumnBatch(sch, b0.columns, jax.device_put(mask),
                             b0.dicts).packed_numpy(hint=64)
    assert cnt3 == cnt
    assert all(np.array_equal(out3[k], out[k]) for k in data)


def test_deferred_metrics_resolve_in_snapshot():
    """Device-resident counts recorded via add_deferred resolve by the time
    collect_plan_metrics snapshots (the shuffle writer's packed fetch makes
    them host-known), and never pin batches (weakref)."""
    import numpy as np

    from arrow_ballista_tpu.ops.physical import MetricsSet, deferred_rows
    from arrow_ballista_tpu.models.batch import ColumnBatch
    from arrow_ballista_tpu.models.schema import Field, INT64, Schema

    sch = Schema([Field("v", INT64)])
    b = ColumnBatch.from_numpy(sch, {"v": np.arange(10)})
    b._num_rows = None  # simulate a device-only count
    ms = MetricsSet()
    deferred_rows(ms, "output_rows", b)
    assert "output_rows" not in ms.to_dict()  # not host-known yet: queued
    b._num_rows = 10  # the packed fetch would set this
    assert ms.to_dict()["output_rows"] == 10
    assert ms.to_dict()["output_rows"] == 10  # resolves once, then sticks

    ms2 = MetricsSet()
    b2 = ColumnBatch.from_numpy(sch, {"v": np.arange(4)})
    b2._num_rows = None
    deferred_rows(ms2, "output_rows", b2)
    del b2  # GC'd unmaterialized: entry must resolve (to 0), not linger
    import gc

    gc.collect()
    assert ms2.to_dict().get("output_rows") == 0
