import numpy as np
import pytest

from arrow_ballista_tpu import (
    BallistaConfig,
    ColumnBatch,
    Field,
    INT64,
    STRING,
    Schema,
    concat_batches,
    decimal,
)
from arrow_ballista_tpu.utils.errors import ConfigurationError


def make_batch():
    schema = Schema([
        Field("k", INT64),
        Field("price", decimal(2)),
        Field("flag", STRING),
    ])
    data = {
        "k": np.array([1, 2, 3], dtype=np.int64),
        "price": np.array([1050, 2099, 399], dtype=np.int64),  # $10.50, $20.99, $3.99
        "flag": np.array([0, 1, 0], dtype=np.int32),
    }
    return ColumnBatch.from_numpy(schema, data, dicts={"flag": np.array(["A", "N"], dtype=object)})


def test_batch_roundtrip_pandas():
    b = make_batch()
    assert b.num_rows == 3
    assert b.capacity >= 3
    df = b.to_pandas()
    assert list(df["k"]) == [1, 2, 3]
    assert list(df["flag"]) == ["A", "N", "A"]
    np.testing.assert_allclose(df["price"], [10.50, 20.99, 3.99])


def test_batch_to_arrow():
    t = make_batch().to_arrow()
    assert t.num_rows == 3
    assert t.column("flag").to_pylist() == ["A", "N", "A"]


def test_concat_batches():
    b = make_batch()
    out = concat_batches(b.schema, [b, b])
    assert out.num_rows == 6
    df = out.to_pandas()
    assert list(df["k"]) == [1, 2, 3, 1, 2, 3]


def test_int64_preserved_through_device():
    # x64 must be on: decimals are int64 fixed-point.
    b = make_batch()
    assert str(b.columns["price"].dtype) == "int64"


def test_config_validation():
    cfg = BallistaConfig.builder().set("ballista.shuffle.partitions", "8").build()
    assert cfg.shuffle_partitions == 8
    assert cfg.batch_size == 1 << 17
    with pytest.raises(ConfigurationError):
        BallistaConfig({"ballista.bogus": 1})
    with pytest.raises(ConfigurationError):
        BallistaConfig({"ballista.shuffle.partitions": "abc"})


def test_wire_narrowing_mixed_width_files(tmp_path):
    """Two shuffle files for one partition — one int32-narrowed, one kept
    int64 (values out of range) — read back as one int64 batch."""
    import numpy as np

    from arrow_ballista_tpu.models.ipc import read_ipc_files, write_ipc_rows
    from arrow_ballista_tpu.models.schema import Field, INT64, Schema

    sch = Schema([Field("v", INT64)])
    small = {"v": np.arange(100, dtype=np.int64)}
    big = {"v": np.arange(100, dtype=np.int64) + 2**40}
    p1, p2 = str(tmp_path / "a.arrow"), str(tmp_path / "b.arrow")
    write_ipc_rows(sch, small, {}, p1)
    write_ipc_rows(sch, big, {}, p2)

    import pyarrow as pa
    import pyarrow.ipc as ipc

    assert ipc.open_file(pa.memory_map(p1)).schema.field("v").type == pa.int32()
    assert ipc.open_file(pa.memory_map(p2)).schema.field("v").type == pa.int64()

    batches = read_ipc_files([p1, p2], sch)
    vals = np.concatenate([b.compacted_numpy()["v"] for b in batches])
    assert vals.dtype == np.int64
    assert sorted(vals) == sorted(list(small["v"]) + list(big["v"]))
