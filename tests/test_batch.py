import numpy as np
import pytest

from arrow_ballista_tpu import (
    BallistaConfig,
    ColumnBatch,
    Field,
    INT64,
    STRING,
    Schema,
    concat_batches,
    decimal,
)
from arrow_ballista_tpu.utils.errors import ConfigurationError


def make_batch():
    schema = Schema([
        Field("k", INT64),
        Field("price", decimal(2)),
        Field("flag", STRING),
    ])
    data = {
        "k": np.array([1, 2, 3], dtype=np.int64),
        "price": np.array([1050, 2099, 399], dtype=np.int64),  # $10.50, $20.99, $3.99
        "flag": np.array([0, 1, 0], dtype=np.int32),
    }
    return ColumnBatch.from_numpy(schema, data, dicts={"flag": np.array(["A", "N"], dtype=object)})


def test_batch_roundtrip_pandas():
    b = make_batch()
    assert b.num_rows == 3
    assert b.capacity >= 3
    df = b.to_pandas()
    assert list(df["k"]) == [1, 2, 3]
    assert list(df["flag"]) == ["A", "N", "A"]
    np.testing.assert_allclose(df["price"], [10.50, 20.99, 3.99])


def test_batch_to_arrow():
    t = make_batch().to_arrow()
    assert t.num_rows == 3
    assert t.column("flag").to_pylist() == ["A", "N", "A"]


def test_concat_batches():
    b = make_batch()
    out = concat_batches(b.schema, [b, b])
    assert out.num_rows == 6
    df = out.to_pandas()
    assert list(df["k"]) == [1, 2, 3, 1, 2, 3]


def test_int64_preserved_through_device():
    # x64 must be on: decimals are int64 fixed-point.
    b = make_batch()
    assert str(b.columns["price"].dtype) == "int64"


def test_config_validation():
    cfg = BallistaConfig.builder().set("ballista.shuffle.partitions", "8").build()
    assert cfg.shuffle_partitions == 8
    assert cfg.batch_size == 1 << 17
    with pytest.raises(ConfigurationError):
        BallistaConfig({"ballista.bogus": 1})
    with pytest.raises(ConfigurationError):
        BallistaConfig({"ballista.shuffle.partitions": "abc"})
