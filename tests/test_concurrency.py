"""Seeded-interleaving stress tests + runtime lock-order shim tests.

Part 1 — deterministic schedule exploration.  A cooperative scheduler
hands a single execution token between registered threads; switch
decisions are drawn from a seeded RNG at traced line events (sys.settrace
inside package code) and at lock-acquire spin points, so each seed
replays one exact interleaving and 50+ seeds sweep genuinely different
schedules.  The threads drive the SchedulerServer's real concurrent
entry points against each other:

- two producers reporting task statuses through ``update_task_status``
  (the inbox-append + coalesced-TaskUpdating-post protocol), one of them
  completing a *speculative duplicate* attempt so the dedup/cancel path
  runs,
- ``cancel_job`` racing both,
- one drainer playing the event loop: it pops posted events and
  dispatches ``_on_event`` exactly as ``EventLoop._run`` would,
  preserving the production single-consumer invariant while every
  producer/drainer interleaving is explored.

Invariants checked after every seed: no thread raised, the status inbox
drained to empty, the posted-event queue drained to empty, the job ended
in exactly one terminal state, per-partition attempt bookkeeping stayed
consistent (one winner, the speculative loser cancelled at most once),
and the attempt log holds no duplicate (partition, attempt) entries.

Part 2 — unit tests for ``analysis/lock_order.py`` (proxy recording,
Condition integration, validate() classification against a fixture repo,
env gating) and regression tests for concurrency fixes shipped with the
analyzer (KvServer txn seq capture, stop-before-start on socket servers).
"""
import os
import queue
import random
import sys
import threading
import time

import pytest

from arrow_ballista_tpu.analysis import lock_order
from arrow_ballista_tpu.scheduler.scheduler import (
    SchedulerConfig,
    SchedulerServer,
    TaskLauncher,
)
from tests.test_scheduler import fake_success, physical_plan

PKG_DIR = os.path.dirname(
    os.path.abspath(lock_order.__file__.replace("analysis", "")))
THIS_FILE = os.path.abspath(__file__)

# raw primitives for the interleaver's own machinery — must never be the
# yielding wrappers the tests install
_RAW_LOCK = lock_order._RAW_LOCK
_RAW_CONDITION = lock_order._RAW_CONDITION

_tls = threading.local()


# --------------------------------------------------------------------------
# deterministic cooperative scheduler
# --------------------------------------------------------------------------

class Interleaver:
    """One-token scheduler: exactly one registered thread runs at a time;
    the seeded RNG decides every handoff, so a seed IS a schedule."""

    def __init__(self, seed: int, switch_prob: float = 0.2):
        self.rng = random.Random(seed)
        self.switch_prob = switch_prob
        self._cond = _RAW_CONDITION(_RAW_LOCK())
        self._runnable = []
        self._current = None
        self._started = False
        self.errors = []

    # --- worker-side protocol -------------------------------------------
    def _enter(self, idx: int) -> None:
        with self._cond:
            while not (self._started and self._current == idx):
                if not self._cond.wait(timeout=30.0):
                    raise RuntimeError("interleaver start stalled")

    def _leave(self, idx: int) -> None:
        with self._cond:
            self._runnable.remove(idx)
            if self._current == idx and self._runnable:
                self._current = self.rng.choice(self._runnable)
            self._cond.notify_all()

    def maybe_switch(self, idx: int) -> None:
        if self._current != idx:  # trace fired outside our token window
            return
        if getattr(_tls, "in_sched", False):
            return
        if self.rng.random() < self.switch_prob:
            self.switch(idx)

    def switch(self, idx: int, force: bool = False) -> None:
        """Hand the token to a seeded choice of runnable thread and block
        until it comes back.  ``force`` (lock spins, idle drains) demands
        a DIFFERENT thread; with nobody else runnable it briefly sleeps
        instead, letting unregistered background threads (pool workers)
        make progress under the GIL.

        The in_sched guard keeps the tracer from re-entering: this
        method's own lines are in a traced file, and a nested switch
        would self-deadlock on the non-reentrant condition."""
        if getattr(_tls, "in_sched", False):
            return
        _tls.in_sched = True
        try:
            self._switch_locked(idx, force)
        finally:
            _tls.in_sched = False

    def _switch_locked(self, idx: int, force: bool) -> None:
        with self._cond:
            others = [i for i in self._runnable if i != idx]
            if not others:
                if force:
                    self._cond.release()
                    try:
                        time.sleep(0.001)
                    finally:
                        self._cond.acquire()
                return
            nxt = self.rng.choice(others if force else self._runnable)
            if nxt == idx:
                return
            self._current = nxt
            self._cond.notify_all()
            while self._current != idx:
                if not self._cond.wait(timeout=30.0):
                    raise RuntimeError("interleaver stalled (deadlock?)")

    def _tracer(self, idx: int):
        def trace(frame, event, arg):
            fn = frame.f_code.co_filename
            if not (fn.startswith(PKG_DIR) or fn == THIS_FILE):
                return None
            if event == "line":
                self.maybe_switch(idx)
            return trace

        return trace

    # --- driver ----------------------------------------------------------
    def run(self, fns, timeout: float = 60.0) -> None:
        def make(idx, fn):
            def worker():
                try:
                    self._enter(idx)
                    _tls.idx = idx
                    sys.settrace(self._tracer(idx))
                    try:
                        fn()
                    finally:
                        sys.settrace(None)
                        _tls.idx = None
                except BaseException as e:  # noqa: BLE001 — reported below
                    self.errors.append((idx, e))
                finally:
                    self._leave(idx)

            return worker

        threads = [threading.Thread(target=make(i, fn),
                                    name=f"interleave-{i}", daemon=True)
                   for i, fn in enumerate(fns)]
        with self._cond:
            self._runnable = list(range(len(fns)))
        for t in threads:
            t.start()
        with self._cond:
            self._current = self.rng.choice(self._runnable)
            self._started = True
            self._cond.notify_all()
        for t in threads:
            t.join(timeout)
        alive = [t.name for t in threads if t.is_alive()]
        assert not alive, f"interleaved threads deadlocked: {alive}"


class _YieldLock:
    """Lock wrapper installed as ``threading.Lock``/``RLock`` during an
    interleaved run: a *registered* thread never blocks while holding the
    token — it spins try-acquire and force-yields between attempts, so a
    parked lock holder always gets scheduled to release.  Unregistered
    threads (pool workers, thread bootstrap) fall through to a normal
    blocking acquire."""

    def __init__(self, sched: Interleaver, raw):
        self._sched = sched
        self._raw = raw
        # threading.Condition steals _is_owned at construction when the
        # lock has one (RLock); without this, its try-acquire ownership
        # probe misreports reentrant locks and notify() raises
        if hasattr(raw, "_is_owned"):
            self._is_owned = raw._is_owned

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        idx = getattr(_tls, "idx", None)
        if idx is None or not blocking:
            return self._raw.acquire(blocking, timeout)
        spins = 0
        while not self._raw.acquire(False):
            self._sched.switch(idx, force=True)
            spins += 1
            if spins > 200_000:
                raise RuntimeError("lock spin livelock")
        return True

    def release(self) -> None:
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class RecordingLauncher(TaskLauncher):
    def __init__(self):
        self.launched = []
        self.cancelled_jobs = []
        self.cancelled_tasks = []

    def launch_tasks(self, executor_id, tasks):
        self.launched.append((executor_id, tasks))

    def cancel_tasks(self, executor_id, job_id):
        self.cancelled_jobs.append((executor_id, job_id))

    def cancel_task(self, executor_id, task):
        self.cancelled_tasks.append((executor_id, task))

    def clean_job_data(self, executor_id, job_id):
        pass




def _run_one_schedule(seed: int):
    """Build a scheduler + a running 3-partition job with one speculative
    duplicate in flight, then race producers/canceller/drainer under the
    seeded schedule.  Returns (server, launcher, graph, trace)."""
    sched = Interleaver(seed)
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    threading.Lock = lambda: _YieldLock(sched, orig_lock())
    threading.RLock = lambda: _YieldLock(sched, orig_rlock())
    try:
        launcher = RecordingLauncher()
        server = SchedulerServer(launcher, SchedulerConfig(
            job_data_cleanup_delay_s=-1.0))
        # no server.init(): the drainer thread IS the event loop here
        from arrow_ballista_tpu.scheduler.execution_graph import ExecutionGraph

        job_id = f"job-{seed}"
        # a fresh plan per run: build() consumes the stage tree
        graph = ExecutionGraph.build(job_id, physical_plan(partitions=3))
        primaries = []
        while True:
            t = graph.pop_next_task("exec-A")
            if t is None:
                break
            primaries.append(t)
        assert len(primaries) == 3
        spec = graph.launch_speculative(
            1, primaries[0].task.partition, "exec-B")
        assert spec is not None
        server.jobs.accept_job(job_id)
        server.jobs.submit_job(job_id, graph)

        state = {"done": 0, "trace": []}

        def producer_primary():
            for t in primaries:
                server.update_task_status(
                    "exec-A", [fake_success(t, "exec-A")])
            state["done"] += 1

        def producer_speculative():
            server.update_task_status("exec-B", [fake_success(spec, "exec-B")])
            state["done"] += 1

        def canceller():
            server.cancel_job(job_id)
            state["done"] += 1

        def drainer():
            q = server._event_loop._queue
            while True:
                try:
                    _, ev = q.get_nowait()
                except queue.Empty:
                    if state["done"] == 3 and q.empty():
                        return
                    sched.switch(3, force=True)
                    continue
                state["trace"].append(type(ev).__name__)
                try:
                    server._on_event(ev)
                except Exception as exc:  # noqa: BLE001 — mirror EventLoop
                    server._on_event_error(ev, exc)

        sched.run([producer_primary, producer_speculative, canceller,
                   drainer])
        assert not sched.errors, \
            f"seed {seed}: thread(s) raised: {sched.errors}"
        server._launch_pool.shutdown(wait=True)
        return server, launcher, graph, tuple(state["trace"])
    finally:
        threading.Lock, threading.RLock = orig_lock, orig_rlock


SEEDS = range(50)


def test_seeded_interleavings_hold_invariants():
    distinct_traces = set()
    for seed in SEEDS:
        server, launcher, graph, trace = _run_one_schedule(seed)
        distinct_traces.add(trace)
        ctx = f"seed {seed} (trace {trace})"
        # inbox + event queue fully drained: the coalescing protocol never
        # strands a posted-but-undrained report
        assert server._status_inbox == {}, ctx
        assert server._event_loop.queue_depth() == 0, ctx
        # exactly one stable terminal state: stage 2 never ran (no
        # executors registered), so the cancel always lands eventually
        st = server.jobs.get_status(f"job-{seed}")
        assert st is not None and st.state == "cancelled", \
            f"{ctx}: state={getattr(st, 'state', None)}"
        # attempt bookkeeping: the audit log never double-registers an
        # attempt, and a finished partition has exactly one winner
        stage = graph.stages[1]
        keys = [(e["partition"], e["attempt"], e["stage_attempt"])
                for e in stage.attempt_log]
        assert len(keys) == len(set(keys)), ctx
        for p, info in enumerate(stage.task_infos):
            if info is not None and info.state == "success":
                assert p not in stage.speculative_tasks, ctx
        # speculative dedup: at most one loser-cancel for the duplicated
        # partition, and exactly one authoritative winner — the audit log
        # may record both attempts as succeeded (each did, on its own
        # executor), but task_infos/outputs carry a single attempt's result
        assert len(launcher.cancelled_tasks) <= 1, ctx
        for p, (executor_id, writes) in stage.outputs.items():
            info = stage.task_infos[p]
            assert info is not None and info.state == "success", ctx
            assert info.executor_id == executor_id, ctx
            assert writes, ctx
    # the sweep actually explored different schedules
    assert len(distinct_traces) >= 2, distinct_traces


def test_same_seed_replays_same_schedule():
    _, launcher_a, _, trace_a = _run_one_schedule(7)
    _, launcher_b, _, trace_b = _run_one_schedule(7)
    assert trace_a == trace_b
    assert len(launcher_a.cancelled_tasks) == len(launcher_b.cancelled_tasks)


# --------------------------------------------------------------------------
# lock_order runtime shim
# --------------------------------------------------------------------------

class TestLockOrderShim:
    def test_install_uninstall_restores_constructors(self):
        was_installed = lock_order._installed
        try:
            lock_order.install()
            assert threading.Lock is not lock_order._RAW_LOCK
            lock_order.install()  # idempotent
            lock_order.uninstall()
            assert threading.Lock is lock_order._RAW_LOCK
            assert threading.RLock is lock_order._RAW_RLOCK
            assert threading.Condition is lock_order._RAW_CONDITION
        finally:
            if was_installed:
                lock_order.install()
            else:
                lock_order.uninstall()

    def test_proxy_records_nested_edges_and_releases(self):
        lock_order.reset()
        try:
            a = lock_order._LockProxy(lock_order._RAW_LOCK(), ("/x.py", 1))
            b = lock_order._LockProxy(lock_order._RAW_LOCK(), ("/x.py", 2))
            with a:
                with b:
                    pass
            # release popped `a`'s stack entry, so this is a fresh edge in
            # the other direction, not a nested re-acquire
            with b:
                with a:
                    pass
            snap = lock_order._recorder.snapshot()
            assert snap == {((("/x.py", 1)), ("/x.py", 2)): 1,
                            ((("/x.py", 2)), ("/x.py", 1)): 1}
        finally:
            lock_order.reset()

    def test_condition_wait_notify_through_proxy(self):
        lock_order.reset()
        try:
            proxy = lock_order._LockProxy(
                lock_order._RAW_RLOCK(), ("/c.py", 1))
            cond = lock_order._RAW_CONDITION(proxy)
            hits = []

            def waiter():
                with cond:
                    while not hits:
                        if not cond.wait(timeout=10.0):
                            return
                    hits.append("woke")

            t = threading.Thread(target=waiter, daemon=True)
            t.start()
            time.sleep(0.05)
            with cond:
                hits.append("set")
                cond.notify_all()  # raises without the _is_owned delegate
            t.join(timeout=10.0)
            assert not t.is_alive() and hits == ["set", "woke"]
            # wait() released the proxy while blocked: another thread's
            # acquire during the wait window must not have deadlocked,
            # and the recorder stack is balanced (next acquire = no edge)
            with proxy:
                pass
            assert lock_order._recorder.snapshot() == {}
        finally:
            lock_order.reset()

    def test_validate_classifies_edges(self, tmp_path):
        pkg = tmp_path / "arrow_ballista_tpu"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            "import threading\n\n\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"        # line 6
            "        self._b = threading.Lock()\n"        # line 7
            "        self._c = threading.Lock()\n"        # line 8
            "\n"
            "    def f(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n")
        mod = str(tmp_path / "arrow_ballista_tpu" / "mod.py")
        site_a, site_b, site_c = (mod, 6), (mod, 7), (mod, 8)
        lock_order.reset()
        try:
            rec = lock_order._recorder
            rec.edges[(site_a, site_b)] = 3          # predicted: a -> b
            rec.edges[(site_b, site_a)] = 1          # inversion of a -> b
            rec.edges[(site_a, site_c)] = 1          # no static path at all
            rec.edges[(site_a, (mod, 999))] = 1      # unmapped end
            rep = lock_order.validate(str(tmp_path))
            assert rep.checked == 3 and rep.unknown == 1
            assert len(rep.contradicted) == 1 and "_b" in rep.contradicted[0]
            assert len(rep.unpredicted) == 1 and "_c" in rep.unpredicted[0]
            assert not rep.ok
            with pytest.raises(AssertionError):
                lock_order.assert_consistent(str(tmp_path))
        finally:
            lock_order.reset()

    def test_enabled_follows_env_flag(self, monkeypatch):
        monkeypatch.setenv("BALLISTA_LOCK_ORDER_RUNTIME", "1")
        assert lock_order.enabled() is True
        monkeypatch.setenv("BALLISTA_LOCK_ORDER_RUNTIME", "0")
        assert lock_order.enabled() is False
        monkeypatch.delenv("BALLISTA_LOCK_ORDER_RUNTIME")
        assert lock_order.enabled() is False  # config default


# --------------------------------------------------------------------------
# regression tests for fixes shipped with the analyzer
# --------------------------------------------------------------------------

def test_kv_txn_returns_its_own_seq_under_concurrency():
    """KvServer._txn must hand each client the seq of ITS OWN last op —
    reading self._seq after leaving _log_lock could return a concurrent
    txn's later seq, making watch cursors skip events."""
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer

    srv = KvServer()
    try:
        results = {}
        barrier = threading.Barrier(8)

        def client(i):
            barrier.wait()
            for j in range(25):
                reply, _ = srv._txn(
                    {"ops": [["put", "s", f"k-{i}-{j}", "v"]]}, b"")
                assert reply["ok"]
                results[(i, j)] = reply["seq"]

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        seqs = sorted(results.values())
        # every single-op txn observed a distinct seq, with no gaps: each
        # response carried the head as of ITS append, not a later one
        assert seqs == list(range(1, 201))
    finally:
        srv.stop()


def test_socket_servers_tolerate_stop_before_start():
    """socketserver.shutdown() blocks forever unless serve_forever is
    running; stop() on a constructed-but-never-started server must not
    hang (it closes the socket and returns)."""
    from arrow_ballista_tpu.net.rpc import RpcServer
    from arrow_ballista_tpu.obs.http import ObsHttpServer

    done = []

    def exercise():
        rpc = RpcServer("127.0.0.1", 0)
        rpc.stop()
        obs = ObsHttpServer("127.0.0.1", 0, {})
        obs.stop()
        done.append(True)

    t = threading.Thread(target=exercise, daemon=True)
    t.start()
    t.join(timeout=10.0)
    assert done, "stop() before start() hung"
