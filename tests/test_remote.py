"""Remote-mode integration: network scheduler + executors + client.

Parity: the reference's distributed flow (client -> SchedulerGrpc ->
executors -> Arrow Flight result fetch).  Executors get SEPARATE work
dirs, so inter-stage shuffle reads exercise the remote data-plane fetch
(reference shuffle_reader.rs remote path), not just the local-file fast
path; serde round-trips every plan that crosses a process boundary.
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu import serde
from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService
    from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig

    sched = SchedulerNetService(
        "127.0.0.1", 0,
        config=BallistaConfig({"ballista.shuffle.partitions": "4"}),
        scheduler_config=SchedulerConfig(task_distribution="round-robin"))
    sched.start()
    executors = []
    for i in range(2):
        work = str(tmp_path_factory.mktemp(f"exec{i}"))
        ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                            work_dir=work, concurrent_tasks=4,
                            executor_id=f"net-exec-{i}")
        ex.start()
        executors.append(ex)
    yield sched, executors
    for ex in executors:
        ex.stop(notify=False)
    sched.stop()


@pytest.fixture(scope="module")
def ctx(cluster):
    sched, _ = cluster
    c = BallistaContext.remote("127.0.0.1", sched.port,
                               BallistaConfig({"ballista.shuffle.partitions": "4"}))
    rng = np.random.default_rng(3)
    n = 10_000
    c.register_table("sales", pa.table({
        "region": pa.array(rng.integers(0, 6, n).astype(np.int64)),
        "amount": pa.array(rng.integers(1, 500, n).astype(np.int64)),
        "item": pa.array(rng.integers(0, 50, n).astype(np.int64)),
    }))
    return c


def test_remote_aggregate(ctx):
    got = ctx.sql("select region, sum(amount) as s, count(*) as n "
                  "from sales group by region order by region").to_pandas()
    assert len(got) == 6
    assert int(got.n.sum()) == 10_000


def test_remote_join_and_shuffle_crosses_executors(cluster, ctx):
    _, executors = cluster
    got = ctx.sql(
        "select item, count(*) as n from sales where amount > 250 "
        "group by item order by n desc, item limit 5").to_pandas()
    assert len(got) == 5
    # both executors must have participated (separate work dirs)
    import os

    def has_job_dirs(ex):
        return any(os.scandir(ex.work_dir))

    assert all(has_job_dirs(ex) for ex in executors), \
        "expected tasks on every executor"


def test_remote_matches_local(ctx):
    sql = ("select region, min(amount) as lo, max(amount) as hi "
           "from sales group by region order by region")
    remote = ctx.sql(sql).to_pandas()
    # same data locally
    local_ctx = BallistaContext.local()
    tables = ctx._remote  # rebuild the same table from the remote fixture rng
    rng = np.random.default_rng(3)
    n = 10_000
    t = pa.table({
        "region": pa.array(rng.integers(0, 6, n).astype(np.int64)),
        "amount": pa.array(rng.integers(1, 500, n).astype(np.int64)),
        "item": pa.array(rng.integers(0, 50, n).astype(np.int64)),
    })
    local_ctx.register_table("sales", t)
    local = local_ctx.sql(sql).to_pandas()
    pd.testing.assert_frame_equal(remote, local, check_dtype=False)


def test_remote_external_table_and_show(ctx, tmp_path):
    import pyarrow.parquet as pq

    path = str(tmp_path / "ext.parquet")
    pq.write_table(pa.table({"x": pa.array([1, 2, 3], type=pa.int64())}), path)
    ctx.sql(f"create external table ext stored as parquet location '{path}'")
    assert "ext" in ctx._remote.list_tables()
    got = ctx.sql("select sum(x) as s from ext").to_pandas()
    assert int(got.s[0]) == 6


def test_remote_error_propagates(ctx):
    from arrow_ballista_tpu.utils.errors import BallistaError

    with pytest.raises(BallistaError):
        ctx.sql("select nope from sales").to_pandas()


def test_serde_roundtrip_tpch_plans():
    """Every TPC-H physical plan must round-trip the wire encoding."""
    from benchmarks.queries import QUERIES
    from benchmarks.schema import TABLES
    from arrow_ballista_tpu.catalog import SchemaCatalog, TableProvider
    from arrow_ballista_tpu.ops.physical import CsvScanExec
    from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner
    from arrow_ballista_tpu.sql.optimizer import optimize
    from arrow_ballista_tpu.sql.parser import parse_sql
    from arrow_ballista_tpu.sql.planner import SqlToRel

    class FakeTbl(TableProvider):
        def __init__(self, name, schema):
            self.name, self.schema = name, schema

        def scan(self, projection, filters, target_partitions):
            sch = self.schema if projection is None else self.schema.project(projection)
            scan = CsvScanExec.__new__(CsvScanExec)
            scan._schema = sch
            scan.filters = list(filters)
            scan._filter_compiler = scan._filter_fn = None
            scan.table_schema = self.schema
            scan.delimiter = "|"
            scan.has_header = False
            scan.files = [f"/data/{self.name}.tbl"]
            scan.groups = [scan.files]
            return scan

        def row_count(self):
            return 1_000_000

    catalog = SchemaCatalog()
    for name, schema in TABLES.items():
        catalog.register(FakeTbl(name, schema))
    config = BallistaConfig({"ballista.shuffle.partitions": "4"})

    for q, sql in QUERIES.items():
        logical = optimize(SqlToRel(catalog).plan(parse_sql(sql)))
        planned = PhysicalPlanner(catalog, config).plan_query(logical)
        obj = serde.plan_to_obj(planned.plan)
        back = serde.plan_from_obj(obj)
        assert serde.plan_to_obj(back) == obj, f"q{q} serde not stable"
        assert back.schema.names() == planned.plan.schema.names(), f"q{q} schema"


def test_explain_over_the_wire(ctx):
    """EXPLAIN plans on the scheduler (it owns the catalog remotely)."""
    out = ctx.sql("EXPLAIN select region, sum(amount) s from sales group by region").to_pandas()
    assert out.plan_type.tolist() == ["logical_plan", "physical_plan"]
    assert "HashAggregateExec" in out.plan.iloc[1]


def test_scheduler_driven_job_data_cleanup(tmp_path):
    """Finished jobs' shuffle dirs are removed by the scheduler's delayed
    remove_job_data fanout (reference executor_manager.rs:231-253 +
    grpc.rs clean_job_data) — well before the executor TTL janitor."""
    import os
    import time

    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService
    from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig

    sched = SchedulerNetService(
        "127.0.0.1", 0,
        config=BallistaConfig({"ballista.shuffle.partitions": "2"}),
        scheduler_config=SchedulerConfig(job_data_cleanup_delay_s=0.5))
    sched.start()
    work = str(tmp_path / "work")
    ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                        work_dir=work, concurrent_tasks=2,
                        executor_id="cleanup-exec")
    ex.start()
    try:
        c = BallistaContext.remote("127.0.0.1", sched.port,
                                   BallistaConfig(
                                       {"ballista.shuffle.partitions": "2"}))
        rng = np.random.default_rng(7)
        c.register_table("t", pa.table({
            "g": pa.array(rng.integers(0, 4, 2000).astype(np.int64)),
            "v": pa.array(rng.integers(0, 9, 2000).astype(np.int64))}))
        out = c.sql("select g, sum(v) s from t group by g order by g").to_pandas()
        assert len(out) == 4
        # the group-by produced shuffle files under <work>/<job>/...
        # the fanout fires ~0.5 s after completion
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            leftovers = [d for d in os.listdir(work)
                         if os.path.isdir(os.path.join(work, d))]
            if not leftovers:
                break
            time.sleep(0.2)
        assert not leftovers, f"job dirs survived cleanup: {leftovers}"
        c.shutdown()
    finally:
        ex.stop(notify=False)
        sched.stop()
