"""Live observability plane: watch streams, progress/ETA, in-flight
doctor, SLO burn rates (ISSUE 17).

Five layers, matching how the PR is built:

  1. journal watch subscriptions: bounded per-subscriber queues, strict
     ordering, overflow -> one leading ``watch.gap`` event (never an
     emit()-side block), job filtering, reset/close lifecycle;
  2. progress/ETA estimator: fraction + per-stage counts on synthetic
     half-finished graphs, quantile ETA with the unresolved-stage
     widening, front-loaded vs back-loaded fixtures, monotonic clamp;
  3. in-flight doctor: a 2 s ``executor.task.slow`` straggler raises an
     ``alert.raised`` while the job RUNS and clears on completion;
     journal backpressure trips the standing ``journal-drops`` alert;
  4. SLO tracker: multi-window burn-rate math, window pruning, fleet
     sample merging, null-object wiring (and the wire-silence contract:
     live plane off => no threads, no registry keys, no subscribers);
  5. e2e watch: a standalone query watched end-to-end (ordered events,
     monotone fraction, one terminal frame), the REST NDJSON stream, and
     one chaos-marked fleet scenario — the owning shard killed mid-watch,
     the stream continuing through lease adoption with the
     ``lease.adopt`` marker in-band, no duplicates, no lost terminal.
"""
import json
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu import faults
from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.obs import journal
from arrow_ballista_tpu.obs.live import CLEAR_AFTER, LiveDoctor
from arrow_ballista_tpu.obs.progress import (
    job_progress,
    monotonic_fraction,
    render_progress_bar,
)
from arrow_ballista_tpu.obs.slo import (
    NullSloTracker,
    SloPolicy,
    SloTracker,
    merge_samples,
    tracker_from_config,
)
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(autouse=True)
def _journal_on():
    """Fresh, enabled journal per test (enable-only switch: standalone
    cluster construction never force-disables it)."""
    journal.reset()
    journal.set_enabled(True)
    journal.configure(capacity=4096)
    faults.clear()
    yield
    faults.clear()
    journal.reset()
    journal.set_enabled(False)
    journal.configure(capacity=4096)


def _table(rng, n, groups=7):
    return pa.table({
        "g": pa.array(rng.integers(0, groups, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    })


def _standalone(conf=None, concurrent_tasks=2, num_executors=2):
    base = {"ballista.shuffle.partitions": "4"}
    base.update(conf or {})
    return BallistaContext.standalone(BallistaConfig(base),
                                      concurrent_tasks=concurrent_tasks,
                                      num_executors=num_executors)


def _wait_for(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


SQL = "select g, sum(v) as s, count(*) as n from t group by g order by g"


# --------------------------------------------------------------------------
# 1. watch subscriptions
# --------------------------------------------------------------------------

def test_watch_subscription_orders_events():
    with journal.subscribe(job_id="j1") as sub:
        for i in range(10):
            journal.emit("task.launch", job_id="j1", partition=i)
        got = sub.drain()
    assert [e["attrs"]["partition"] for e in got] == list(range(10))
    seqs = [e["seq"] for e in got]
    assert seqs == sorted(seqs)
    assert all(e["kind"] == "task.launch" for e in got)


def test_watch_subscription_filters_by_job():
    with journal.subscribe(job_id="mine") as sub:
        journal.emit("a", job_id="mine")
        journal.emit("b", job_id="other")
        journal.emit("c", job_id="mine")
        kinds = [e["kind"] for e in sub.drain()]
    assert kinds == ["a", "c"]
    with journal.subscribe() as firehose:  # job_id=None follows everything
        journal.emit("d", job_id="mine")
        journal.emit("e", job_id="other")
        assert [e["kind"] for e in firehose.drain()] == ["d", "e"]


def test_watch_overflow_yields_gap_event_and_keeps_newest():
    with journal.subscribe(job_id="j1", capacity=4) as sub:
        for i in range(20):
            journal.emit("ev", job_id="j1", i=i)
        got = sub.poll(timeout=0)
    # one leading synthetic gap event accounting for every shed event
    assert got[0]["kind"] == "watch.gap"
    assert got[0]["seq"] == 0  # must never dedup on (actor, seq)
    assert got[0]["attrs"]["dropped"] == 16
    # the queue kept the NEWEST capacity events, in order
    assert [e["attrs"]["i"] for e in got[1:]] == [16, 17, 18, 19]


def test_slow_subscriber_never_blocks_emit():
    sub = journal.subscribe(job_id="j1", capacity=8)
    try:
        t0 = time.monotonic()
        for i in range(5000):
            assert journal.emit("ev", job_id="j1", i=i) is not None
        elapsed = time.monotonic() - t0
        # 5000 emits against a saturated, never-drained subscriber must
        # be pure append/shed work — nothing remotely like a block
        assert elapsed < 2.0
        got = sub.drain()
        assert got[0]["kind"] == "watch.gap"
        assert got[0]["attrs"]["dropped"] == 5000 - 8
        assert len(got) == 1 + 8
    finally:
        sub.close()
    assert journal.watcher_count() == 0


def test_closed_subscription_detaches_and_reset_closes():
    sub = journal.subscribe()
    assert journal.watcher_count() == 1
    sub.close()
    assert journal.watcher_count() == 0 and sub.closed
    sub2 = journal.subscribe()
    journal.reset()
    assert sub2.closed and journal.watcher_count() == 0


def test_disabled_journal_watch_is_zero_cost():
    journal.set_enabled(False)
    with journal.subscribe() as sub:
        assert journal.emit("ev", job_id="j1") is None
        assert sub.poll(timeout=0) == []
    assert journal.counters() == (0, 0)


# --------------------------------------------------------------------------
# 2. progress / ETA estimator (synthetic graphs)
# --------------------------------------------------------------------------

class _Task:
    def __init__(self, state, started_at=None):
        self.state = state
        self.started_at = started_at if started_at is not None \
            else time.monotonic()


class _Stage:
    """Duck-typed ExecutionStage: enough surface for job_progress AND
    the live doctor's stage_summary fold."""

    def __init__(self, state, partitions, done=0, running=0, durations=(),
                 stage_id=1):
        self.state = state
        self.partitions = partitions
        self.task_infos = ([_Task("success")] * done
                           + [_Task("running")] * running
                           + [None] * (partitions - done - running))
        self.speculative_tasks = {}
        self.durations = list(durations)
        self.stage_id = stage_id
        self.stage_attempt = 0
        self.planned_partitions = partitions
        self.outputs = {}
        self.attempt_log = []

    def operator_metrics(self):
        return {}


class _Graph:
    def __init__(self, stages, status="running", job_id="synth"):
        self.stages = stages
        self.status = status
        self.job_id = job_id
        self.stats = None


def test_progress_half_finished_graph():
    g = _Graph({1: _Stage("successful", 4, done=4, durations=[0.1] * 4),
                2: _Stage("running", 4, done=0, running=2)})
    p = job_progress(g)
    assert p["fraction"] == 0.5
    assert p["tasks_completed"] == 4 and p["tasks_total"] == 8
    assert p["tasks_running"] == 2
    assert [s["fraction"] for s in p["stages"]] == [1.0, 0.0]
    # 4 remaining tasks x p50 0.1 s over 2 running lanes
    assert p["eta_s"] == pytest.approx(0.2)


def test_progress_terminal_states_clamp():
    g = _Graph({1: _Stage("successful", 4, done=4)}, status="successful")
    p = job_progress(g)
    assert p["fraction"] == 1.0 and p["eta_s"] == 0.0
    g2 = _Graph({1: _Stage("failed", 4, done=1)}, status="failed")
    assert job_progress(g2)["eta_s"] == 0.0


def test_progress_no_completions_no_eta():
    g = _Graph({1: _Stage("running", 4, running=2)})
    p = job_progress(g)
    assert p["eta_s"] is None and p["eta_high_s"] is None


def test_eta_widens_while_unresolved_stages_dominate():
    # front-loaded: the remaining work is in RESOLVED stages -> the
    # completed-duration quantiles describe it, interval stays tight
    front = _Graph({
        1: _Stage("successful", 8, done=8, durations=[0.2] * 8),
        2: _Stage("running", 8, done=4, running=2, durations=[0.2] * 4),
    })
    # back-loaded: same counts, but the remaining tasks sit behind an
    # UNRESOLVED stage whose operators have produced no durations yet
    back = _Graph({
        1: _Stage("successful", 8, done=8, durations=[0.2] * 8),
        2: _Stage("running", 4, done=4, durations=[0.2] * 4),
        3: _Stage("unresolved", 4),
    })
    pf, pb = job_progress(front), job_progress(back)
    assert pf["tasks_total"] - pf["tasks_completed"] == \
        pb["tasks_total"] - pb["tasks_completed"]
    assert pf["eta_basis"]["unresolved_share"] == 0.0
    assert pb["eta_basis"]["unresolved_share"] == 1.0
    # identical quantiles, so only the widening separates the upper bounds
    assert pb["eta_high_s"] > pf["eta_high_s"] * 2.0


def test_monotonic_fraction_and_bar_render():
    floor = 0.0
    for frac, want in ((0.2, 0.2), (0.5, 0.5), (0.3, 0.5), (1.0, 1.0)):
        floor = monotonic_fraction({"fraction": frac}, floor)
        assert floor == want
    bar = render_progress_bar({"fraction": 0.5, "tasks_completed": 4,
                               "tasks_total": 8, "tasks_running": 2,
                               "rows_per_sec": 1234.0, "eta_s": 1.5,
                               "eta_high_s": 3.0, "state": "running"})
    assert "50.0%" in bar and "4/8 tasks" in bar and "eta ~1.5s" in bar


def test_progress_agreement_across_surfaces():
    """One computation, every surface: /api/jobs, the stages endpoint,
    EXPLAIN ANALYZE and a direct fold must report the same fraction."""
    from arrow_ballista_tpu.obs.stats import explain_analyze_report
    from arrow_ballista_tpu.scheduler.rest import RestApi

    ctx = _standalone()
    try:
        ctx.register_table("t", _table(np.random.default_rng(3), 4000))
        ctx.sql(SQL).to_pandas()
        sched = ctx._standalone.scheduler
        job_id = ctx._standalone.last_job_id
        graph = sched.jobs.get_graph(job_id)
        direct = job_progress(graph)

        api = RestApi(sched)
        try:
            entry = [j for j in api._jobs() if j["job_id"] == job_id][0]
            assert entry["progress"] == direct["fraction"]
            assert entry["tasks_completed"] == direct["tasks_completed"]
            assert entry["tasks_total"] == direct["tasks_total"]
            stages = api._stages(job_id)
            assert [s["fraction"] for s in stages] == \
                [s["fraction"] for s in direct["stages"]]
            detail = api._job_detail(job_id)
            assert detail["progress"]["fraction"] == direct["fraction"]
        finally:
            api._httpd.server_close()  # never started; close the socket
        report = explain_analyze_report(graph)
        assert report["progress"]["fraction"] == direct["fraction"]
        assert report["progress"]["tasks_total"] == direct["tasks_total"]
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# 3. in-flight doctor
# --------------------------------------------------------------------------

def _stub_server(graphs=()):
    jobs = types.SimpleNamespace(active_graphs=lambda: list(graphs))
    return types.SimpleNamespace(jobs=jobs, cluster_history=lambda: {})


def test_live_straggler_alert_raised_then_cleared():
    """A 2 s ``executor.task.slow`` straggler must raise an in-flight
    ``alert.raised`` WHILE the job runs, and the alert must clear once
    the job finishes — both visible in the job's journal timeline."""
    ctx = _standalone({
        "ballista.live.enabled": "true",
        "ballista.live.doctor.interval.seconds": "0.15",
    })
    try:
        ctx.register_table("t", _table(np.random.default_rng(23), 4000))
        sched = ctx._standalone.scheduler
        assert sched._live_doctor_thread is not None \
            and sched._live_doctor_thread.is_alive()
        plan = faults.FaultPlan.from_obj({"seed": 21, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 2000, "times": 1,
            "match": {"stage_id": 1, "executor_id": "executor-0"}}]})
        with faults.use_plan(plan):
            ctx.sql(SQL).to_pandas()
        assert plan.events, "the slow failpoint must actually have fired"
        job_id = ctx._standalone.last_job_id

        def kinds():
            return [e["kind"] for e in journal.job_timeline(job_id)]

        assert "alert.raised" in kinds(), \
            "the in-flight doctor must have seen the straggler mid-run"
        raised = [e for e in journal.job_timeline(job_id)
                  if e["kind"] == "alert.raised"]
        assert any(e["attrs"]["rule"] == "straggler" for e in raised)
        f = [e for e in raised if e["attrs"]["rule"] == "straggler"][0]
        assert f["attrs"]["evidence"]["oldest_running_task_s"] > 0.4
        assert "speculation" in f["attrs"]["remedy"]
        # the job left the running set -> the next scan clears the alert
        _wait_for(lambda: "alert.cleared" in kinds(), 5.0,
                  "standing alert must clear after the job finishes")
        cleared = [e for e in journal.job_timeline(job_id)
                   if e["kind"] == "alert.cleared"][0]
        assert cleared["attrs"]["reason"] == "job-finished"
        _wait_for(lambda: sched.live_doctor.alerts_active() == 0, 5.0,
                  "no standing alerts after the run")
    finally:
        ctx.shutdown()


def test_live_doctor_clear_hysteresis_inline():
    """Deterministic raise/clear against a synthetic graph: the alert
    raises on one tripping scan and needs CLEAR_AFTER clean scans."""
    stage = _Stage("running", 4, done=2, running=1,
                   durations=[0.05, 0.06])
    stage.task_infos[2].started_at = time.monotonic() - 10.0  # ancient
    g = _Graph({1: stage}, job_id="live-synth")
    doc = LiveDoctor()
    doc.scan(_stub_server([g]))
    assert doc.alerts_active() == 1
    assert doc.active_findings()[0]["rule"] == "straggler"
    tl = journal.job_timeline("live-synth")
    assert [e["kind"] for e in tl] == ["alert.raised"]
    # same condition still tripping: deduped, no second raise
    doc.scan(_stub_server([g]))
    assert len(journal.job_timeline("live-synth")) == 1
    # condition goes away: needs CLEAR_AFTER consecutive clean scans
    stage.task_infos[2] = _Task("success")
    for i in range(CLEAR_AFTER):
        assert doc.alerts_active() == 1
        doc.scan(_stub_server([g]))
    assert doc.alerts_active() == 0
    kinds = [e["kind"] for e in journal.job_timeline("live-synth")]
    assert kinds == ["alert.raised", "alert.cleared"]


def test_journal_drops_standing_alert():
    """Backpressure alarm: a saturated ring trips the standing
    ``journal-drops`` alert; a reset clears it."""
    journal.configure(capacity=8)
    doc = LiveDoctor()
    doc.scan(_stub_server())
    assert doc.alerts_active() == 0  # nothing dropped yet
    for i in range(50):
        journal.emit("ev", i=i)
    assert journal.counters()[1] > 0
    doc.scan(_stub_server())
    assert doc.alerts_active() == 1
    f = doc.active_findings()[0]
    assert f["rule"] == "journal-drops" and f["job_id"] == ""
    assert f["evidence"]["journal_events_dropped_total"] > 0
    assert "ballista.journal.capacity" in f["remedy"]
    drops_alert = [e for e in journal.snapshot()
                   if e["kind"] == "alert.raised"]
    assert drops_alert and \
        drops_alert[-1]["attrs"]["rule"] == "journal-drops"
    # counters reset (the operator raised capacity / restarted): clears
    journal.reset()
    doc.scan(_stub_server())
    assert doc.alerts_active() == 0


def test_journal_drops_zero_cost_when_disabled():
    journal.set_enabled(False)
    journal.configure(capacity=8)
    for i in range(50):
        journal.emit("ev", i=i)
    assert journal.counters() == (0, 0)
    doc = LiveDoctor()
    doc.scan(_stub_server())
    assert doc.alerts_active() == 0


# --------------------------------------------------------------------------
# 4. SLO tracker
# --------------------------------------------------------------------------

def test_slo_burn_rate_math():
    # window 120 s -> fast window 10 s; p99 target 100 ms
    tr = SloTracker(SloPolicy(100.0, 120.0))
    now = 1_000_000.0
    for i in range(98):
        tr.record(50.0, ok=True, ts=now)
    tr.record(500.0, ok=True, ts=now)   # over target -> violation
    tr.record(50.0, ok=False, ts=now)   # failure -> violation
    snap = tr.snapshot(now=now)
    fast = snap["windows"]["fast"]
    assert fast["count"] == 100 and fast["violations"] == 2
    assert fast["violation_fraction"] == pytest.approx(0.02)
    # 2% observed vs 1% allowed -> burning budget at 2x
    assert fast["burn_rate"] == pytest.approx(2.0)
    assert tr.max_burn_rate(now=now) == pytest.approx(2.0)


def test_slo_window_pruning_and_fast_slow_divergence():
    tr = SloTracker(SloPolicy(100.0, 120.0))
    now = time.time()
    # old violations: outside the 10 s fast window, inside the slow one
    for _ in range(10):
        tr.record(500.0, ok=True, ts=now - 60.0)
    for _ in range(10):
        tr.record(50.0, ok=True, ts=now)
    snap = tr.snapshot()
    assert snap["windows"]["fast"]["violations"] == 0
    assert snap["windows"]["slow"]["violations"] == 10
    # beyond the slow window: pruned entirely on the next record
    tr.record(50.0, ok=True, ts=now + 121.0)
    assert tr.snapshot()["windows"]["slow"]["count"] <= 1


def test_slo_fleet_merge():
    tr = SloTracker(SloPolicy(100.0, 120.0))
    now = time.time()
    tr.record(50.0, ok=True, ts=now)
    sibling = {"slo_fast_count": 99, "slo_fast_violations": 3,
               "slo_slow_count": 99, "slo_slow_violations": 3}
    snap = tr.snapshot(shard_samples=[sibling])
    assert snap["windows"]["fast"]["count"] == 100
    assert snap["windows"]["fast"]["violations"] == 3
    assert snap["windows"]["fast"]["burn_rate"] == pytest.approx(3.0)
    merged = merge_samples([sibling, sibling])
    assert merged["slo_fast_count"] == 198


def test_slo_null_object_and_config_wiring():
    null = tracker_from_config(BallistaConfig())  # target unset -> 0.0
    assert isinstance(null, NullSloTracker) and not null.enabled
    null.record(1e9, ok=False)
    assert null.sample() == {} and null.max_burn_rate() == 0.0
    assert null.snapshot() == {"enabled": False}
    real = tracker_from_config(BallistaConfig({
        "ballista.slo.latency.p99.target.ms": "250",
        "ballista.slo.window.seconds": "600"}))
    assert isinstance(real, SloTracker)
    assert real.policy.p99_target_ms == 250.0
    assert real.policy.fast_window_s == pytest.approx(50.0)


def test_wire_silence_when_live_plane_off():
    """Default config: no live-doctor thread, null SLO tracker, no
    registry sample keys beyond the pre-PR set, no journal subscribers —
    the plane is zero-cost and wire-silent when off."""
    ctx = _standalone()
    try:
        ctx.register_table("t", _table(np.random.default_rng(1), 1000))
        ctx.sql(SQL).to_pandas()
        sched = ctx._standalone.scheduler
        assert sched._live_doctor_thread is None
        assert isinstance(sched.slo, NullSloTracker)
        assert set(sched._registry_sample()) == set(sched._REGISTRY_KEYS)
        assert "slo" not in sched.autoscale_signal()
        assert journal.watcher_count() == 0
        # task statuses carry nothing new: the serde shape is untouched
        from arrow_ballista_tpu import serde
        from arrow_ballista_tpu.scheduler.types import TaskId, TaskStatus

        obj = serde.status_to_obj(TaskStatus(
            TaskId("j", 1, 0, 0), "executor-0", "success"))
        assert not any(k.startswith(("slo", "live", "watch"))
                       for k in obj)
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# 5. e2e watch streams
# --------------------------------------------------------------------------

def _assert_watch_frames(frames, require_events=True):
    """Shared frame-stream contract: ordering, monotone fraction, one
    terminal frame at the very end, no duplicate events."""
    assert frames, "watch stream yielded nothing"
    kinds = [f["t"] for f in frames]
    assert kinds[-1] == "end" and kinds.count("end") == 1
    assert kinds.count("progress") >= 1
    if require_events:
        assert kinds.count("event") >= 1
    seen = set()
    for f in frames:
        if f["t"] != "event" or f["event"].get("kind") == "watch.gap":
            continue
        key = (f["event"].get("actor"), f["event"].get("seq"))
        assert key not in seen, f"duplicate event in stream: {f['event']}"
        seen.add(key)
    fracs = [f["progress"]["fraction"] for f in frames
             if f["t"] == "progress"]
    assert all(a <= b for a, b in zip(fracs, fracs[1:])), \
        f"fraction must be monotonically non-decreasing: {fracs}"
    return frames[-1]


def test_standalone_watch_stream_end_to_end():
    ctx = _standalone()
    try:
        ctx.register_table("t", _table(np.random.default_rng(5), 4000))
        ctx.sql(SQL).to_pandas()
        frames = list(ctx.watch())  # defaults to the last job
        end = _assert_watch_frames(frames)
        assert end["state"] == "successful" and not end["error"]
        ev_kinds = {f["event"]["kind"] for f in frames
                    if f["t"] == "event"}
        assert "job.submitted" in ev_kinds
        assert journal.watcher_count() == 0  # stream detached cleanly
    finally:
        ctx.shutdown()


def test_standalone_watch_live_during_run():
    """Watch a job WHILE it runs: progress frames must appear before the
    terminal frame and the fraction must move."""
    ctx = _standalone({"ballista.speculation.enabled": "false"},
                      concurrent_tasks=1, num_executors=1)
    try:
        ctx.register_table("t", _table(np.random.default_rng(7), 4000))
        plan = faults.FaultPlan.from_obj({"seed": 3, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 150, "times": -1}]})
        frames = []
        errs = []

        def run():
            try:
                ctx.sql(SQL).to_pandas()
            except Exception as e:  # noqa: BLE001 — asserted below
                errs.append(e)

        with faults.use_plan(plan):
            q = threading.Thread(target=run, daemon=True)
            q.start()
            _wait_for(lambda: ctx._standalone.last_job_id is not None,
                      10.0, "job should be submitted")
            for frame in ctx.watch(ctx._standalone.last_job_id,
                                   timeout=60.0):
                frames.append(frame)
            q.join(timeout=30.0)
        assert not errs, errs
        end = _assert_watch_frames(frames)
        assert end["state"] == "successful"
        # a mid-run progress frame existed (not only the 1.0 snapshot)
        fracs = [f["progress"]["fraction"] for f in frames
                 if f["t"] == "progress"]
        assert fracs[0] < 1.0
    finally:
        ctx.shutdown()


def test_rest_watch_stream_ndjson():
    from arrow_ballista_tpu.scheduler.rest import RestApi

    ctx = _standalone()
    try:
        ctx.register_table("t", _table(np.random.default_rng(9), 2000))
        ctx.sql(SQL).to_pandas()
        job_id = ctx._standalone.last_job_id
        api = RestApi(ctx._standalone.scheduler)
        api.start()
        try:
            base = f"http://{api.host}:{api.port}"
            resp = urllib.request.urlopen(
                f"{base}/api/job/{job_id}/watch", timeout=30)
            assert resp.headers["Content-Type"] == "application/x-ndjson"
            frames = [json.loads(line) for line in resp]
            end = _assert_watch_frames(frames)
            assert end["state"] == "successful"
            # 404 for a job nobody ran
            try:
                urllib.request.urlopen(f"{base}/api/job/nope/watch",
                                       timeout=10)
                raise AssertionError("unknown job must 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
            # /api/slo rides the same server (null tracker here)
            slo = json.load(urllib.request.urlopen(f"{base}/api/slo",
                                                   timeout=10))
            assert slo == {"enabled": False}
        finally:
            api.stop()
    finally:
        ctx.shutdown()


def test_slo_feeds_from_completed_jobs_and_reaches_surfaces():
    """A sub-millisecond p99 target makes every real job a violation:
    the burn rate must move on /api/slo, the autoscale signal and the
    prometheus families."""
    ctx = _standalone({
        "ballista.slo.latency.p99.target.ms": "0.001",
        "ballista.slo.window.seconds": "300",
    })
    try:
        ctx.register_table("t", _table(np.random.default_rng(11), 2000))
        ctx.sql(SQL).to_pandas()
        sched = ctx._standalone.scheduler
        assert isinstance(sched.slo, SloTracker)
        snap = sched.slo_report()
        assert snap["enabled"] and \
            snap["windows"]["fast"]["violations"] >= 1
        assert snap["windows"]["fast"]["burn_rate"] > 1.0
        sig = sched.autoscale_signal()
        assert sig["slo"]["burn_rate"] > 1.0
        assert 1 <= sig["slo"]["scale_boost"] <= 4
        sched.sync_journal_metrics()
        sched.metrics.set_slo_burn_rate(
            "fast", snap["windows"]["fast"]["burn_rate"])
        text = sched.metrics.gather()
        assert "# TYPE slo_burn_rate gauge" in text
        assert 'slo_burn_rate{window="fast"}' in text
        assert "# TYPE alerts_active gauge" in text
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# chaos: SIGKILL the owning shard mid-watch -> one continuous stream
# --------------------------------------------------------------------------

FLEET_CONF = {
    "ballista.shuffle.partitions": "4",
    "ballista.journal.enabled": "true",
    "ballista.rpc.connect.timeout.seconds": "1.0",
    "ballista.rpc.read.timeout.seconds": "10.0",
    "ballista.rpc.retry.base.seconds": "0.05",
    "ballista.rpc.retry.cap.seconds": "0.2",
    "ballista.rpc.retry.deadline.seconds": "1.5",
    "ballista.shuffle.local.host_match": "false",
    "ballista.fleet.lease.ttl.seconds": "1.5",
    "ballista.fleet.lease.renew.seconds": "0.4",
    "ballista.fleet.adopt.interval.seconds": "0.4",
    "ballista.fleet.registry.stale.seconds": "5.0",
}


@pytest.mark.chaos
def test_fleet_shard_killed_mid_watch_stream_continues(tmp_path):
    """Kill the owning shard while a client watches its job: the stream
    must continue through lease adoption as ONE timeline — the
    ``lease.adopt`` marker in-band, no duplicate events, the terminal
    frame delivered."""
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.kv import MemoryKv
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService
    from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig

    kv = KvServer(MemoryKv(), "127.0.0.1", 0)
    kv.start()
    sconf = dict(task_distribution="round-robin", executor_timeout_s=3.0,
                 reaper_interval_s=0.3, fleet_lease_ttl_s=1.5,
                 fleet_lease_renew_s=0.4, fleet_adopt_interval_s=0.4,
                 fleet_registry_stale_s=5.0)
    shards, executors, c = [], [], None
    try:
        for _ in range(2):
            s = SchedulerNetService(
                "127.0.0.1", 0, config=BallistaConfig(FLEET_CONF),
                scheduler_config=SchedulerConfig(**sconf),
                cluster_url=f"kv://{kv.host}:{kv.port}")
            s.start()
            shards.append(s)
        eps = [("127.0.0.1", s.port) for s in shards]
        for i in range(2):
            work = tmp_path / f"exec{i}"
            work.mkdir()
            ex = ExecutorServer("127.0.0.1", eps[0][1], "127.0.0.1", 0,
                                work_dir=str(work), concurrent_tasks=1,
                                executor_id=f"watch-exec-{i}",
                                config=BallistaConfig(FLEET_CONF),
                                heartbeat_interval_s=0.4,
                                scheduler_endpoints=eps)
            ex.start()
            executors.append(ex)
        c = BallistaContext.remote(config=BallistaConfig(FLEET_CONF),
                                   endpoints=eps)
        rng = np.random.default_rng(13)
        c.register_table("t", _table(rng, 8000))

        result, errors, frames = [], [], []
        plan = faults.FaultPlan.from_obj({"seed": 5, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 400, "times": -1}]})

        def run_query():
            try:
                result.append(c.sql(SQL).to_pandas())
            except Exception as e:  # noqa: BLE001 — asserted below
                errors.append(e)

        with faults.use_plan(plan):
            q = threading.Thread(target=run_query, daemon=True)
            q.start()
            _wait_for(lambda: shards[0].server._leases, 10.0,
                      "primary shard should claim the job lease")
            job_id = next(iter(shards[0].server._leases))

            def watch():
                try:
                    for frame in c._remote.watch(job_id, timeout=90.0):
                        frames.append(frame)
                except Exception as e:  # noqa: BLE001 — asserted below
                    errors.append(e)

            w = threading.Thread(target=watch, daemon=True)
            w.start()
            _wait_for(lambda: frames, 15.0,
                      "the watch should stream before the kill")
            shards[0].kill()  # in-process kill -9: no goodbyes
            q.join(timeout=90.0)
            w.join(timeout=90.0)

        assert not q.is_alive() and not w.is_alive()
        assert not errors, f"query/watch failed across failover: {errors}"
        end = _assert_watch_frames(frames)
        assert end["state"] == "successful", \
            "the terminal frame must survive the failover"
        ev_kinds = [f["event"]["kind"] for f in frames
                    if f["t"] == "event"]
        assert "lease.adopt" in ev_kinds, \
            "the adoption marker must appear in-band in the stream"
    finally:
        if c is not None:
            c.shutdown()
        for ex in executors:
            try:
                ex.stop(notify=False)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        for s in shards:
            try:
                s.stop()
            except Exception:  # noqa: BLE001
                pass
        try:
            kv.stop()
        except Exception:  # noqa: BLE001
            pass
