"""RIGHT and FULL OUTER joins (reference gets the full set from DataFusion;
SURVEY §1 ENGINE layer).  Oracle: pandas merge on the same data."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(scope="module")
def tables():
    rng = np.random.default_rng(17)
    n_l, n_r = 3_000, 800
    left = pa.table({
        "lk": pa.array(rng.integers(0, 1000, n_l).astype(np.int64)),
        "lv": pa.array(rng.integers(0, 100, n_l).astype(np.int64)),
    })
    right = pa.table({
        "rk": pa.array(rng.integers(500, 1500, n_r).astype(np.int64)),
        "rv": pa.array(rng.integers(0, 100, n_r).astype(np.int64)),
    })
    return left, right


def _norm(df):
    cols = list(df.columns)
    out = df.copy()
    for c in cols:
        out[c] = out[c].astype(np.float64)
    return out.sort_values(cols, kind="mergesort").reset_index(drop=True)


def _run(tables, sql, how, config=None):
    left, right = tables
    ctx = BallistaContext.local(config) if config is None \
        else BallistaContext.standalone(config, concurrent_tasks=2)
    try:
        ctx.register_table("l", left)
        ctx.register_table("r", right)
        got = ctx.sql(sql).to_pandas()
    finally:
        ctx.shutdown()
    want = left.to_pandas().merge(right.to_pandas(), left_on="lk",
                                  right_on="rk", how=how)
    pd.testing.assert_frame_equal(_norm(got), _norm(want[list(got.columns)]),
                                  check_dtype=False, atol=1e-9)
    return got


SQL = "SELECT lk, lv, rk, rv FROM l {} JOIN r ON lk = rk"


def test_right_join_matches_pandas(tables):
    _run(tables, SQL.format("RIGHT"), "right")


def test_right_outer_keyword(tables):
    _run(tables, SQL.format("RIGHT OUTER"), "right")


def test_full_join_matches_pandas(tables):
    got = _run(tables, SQL.format("FULL"), "outer")
    # both sides must show NULL holes
    assert got["lk"].isna().any() and got["rk"].isna().any()


def test_full_join_through_standalone(tables):
    cfg = BallistaConfig({"ballista.shuffle.partitions": "4",
                          "ballista.join.broadcast_threshold": "1"})
    _run(tables, SQL.format("FULL OUTER"), "outer", config=cfg)


def test_right_join_counts(tables):
    left, right = tables
    ctx = BallistaContext.local()
    try:
        ctx.register_table("l", left)
        ctx.register_table("r", right)
        got = ctx.sql("SELECT COUNT(*) AS c FROM l RIGHT JOIN r ON lk = rk").to_pandas()
    finally:
        ctx.shutdown()
    want = len(left.to_pandas().merge(right.to_pandas(), left_on="lk",
                                      right_on="rk", how="right"))
    assert got["c"].tolist() == [want]
