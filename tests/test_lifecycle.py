"""Query lifecycle guardrails: server-side deadlines, cooperative
cancellation, poison-query containment, zombie-task reconciliation.

The invariants under test:

- a deadline is armed at submission, enforced fleet-wide by the
  scheduler's reaper, and rides the checkpoint as an ABSOLUTE expiry;
- the public cancel surface releases every piece of job state (slots,
  admission permits, in-flight tokens) — cancellation leaks nothing;
- the same partition failing with equivalent errors on K distinct
  executors classifies the QUERY as poison: fail fast, refund every
  implicated executor's quarantine streak, skip the retry budget;
- an executor heartbeating tasks for a job the scheduler already closed
  gets the kill re-issued (the lost-cancel-RPC leak), and the disk
  janitor never deletes a live job's workspace;
- retried partitions are steered away from executors that already failed
  them whenever a different alive executor exists (anti-affinity), so
  poison evidence can accumulate — without ever deadlocking a
  single-executor cluster.
"""
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu import faults, serde
from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.ops.physical import (
    CancelToken,
    TaskContext,
    checkpoint,
    current_cancel_token,
    install_cancel_token,
)
from arrow_ballista_tpu.scheduler.execution_graph import ExecutionGraph
from arrow_ballista_tpu.scheduler.types import ExecutorHeartbeat
from arrow_ballista_tpu.utils.config import BallistaConfig
from arrow_ballista_tpu.utils.errors import (
    CancelledError,
    ExecutionError,
    PlanningError,
)

from .test_scheduler import fake_success, physical_plan, scheduler_test

SQL = "select g, sum(v) as s, count(*) as n from t group by g order by g"


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear()
    yield
    faults.clear()


def _ctx(conf_extra=None, num_executors=2):
    conf = {"ballista.shuffle.partitions": "4",
            "ballista.journal.enabled": "true"}
    conf.update(conf_extra or {})
    ctx = BallistaContext.standalone(BallistaConfig(conf),
                                     concurrent_tasks=2,
                                     num_executors=num_executors)
    rng = np.random.default_rng(23)
    ctx.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 7, 4000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 4000).astype(np.int64)),
    }))
    return ctx


def _stall_plan(delay_ms=5000, stage_id=1):
    """Every stage-``stage_id`` task sleeps long enough to outlive the
    test's deadline/cancel window, short enough that the woken task hits
    its cancel checkpoint (and unwinds) well inside the leak sweep."""
    return faults.FaultPlan.from_obj({"seed": 11, "rules": [{
        "site": "executor.task.slow", "action": "delay",
        "delay_ms": delay_ms, "times": -1,
        "match": {"stage_id": stage_id}}]})


def _assert_no_leaks(sched, executors, timeout=15.0):
    """Post-terminal sweep: every reservation, permit and in-flight token
    must be released."""
    deadline = time.monotonic() + timeout
    def residuals():
        out = []
        if any(ex.active_tasks() for ex in executors):
            out.append("in-flight tasks")
        if any(ex.running_task_ids() for ex in executors):
            out.append("cancel tokens")
        if sched.cluster.total_available() != sched.cluster.total_slots():
            out.append("slot reservations")
        if sched.pending_task_count() != 0:
            out.append("pending tasks")
        if sched.jobs.active_graphs():
            out.append("active graphs")
        snap = sched.admission.snapshot()
        if snap["queued"] or snap["running"]:
            out.append("admission permits")
        return out
    while residuals() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not residuals(), f"leaked after terminal status: {residuals()}"


# --------------------------------------------------------------------------
# cooperative cancellation token
# --------------------------------------------------------------------------

def test_cancel_token_checkpoint_units():
    assert current_cancel_token() is None
    checkpoint()                      # no token installed: no-op
    TaskContext().check_cancelled()   # no probe, no token: no-op
    token = CancelToken()
    install_cancel_token(token)
    try:
        assert current_cancel_token() is token
        checkpoint("jobx")            # installed but not cancelled: no-op
        TaskContext(job_id="jobx").check_cancelled()
        token.cancel()
        with pytest.raises(CancelledError, match="jobx"):
            checkpoint("jobx")
        with pytest.raises(CancelledError, match="jobx"):
            TaskContext(job_id="jobx").check_cancelled()
    finally:
        install_cancel_token(None)
    checkpoint("jobx")  # uninstalled: cancelled token no longer observed


def test_cancel_token_is_thread_local():
    token = CancelToken()
    token.cancel()
    install_cancel_token(token)
    try:
        seen = {}

        def other():
            seen["token"] = current_cancel_token()
            checkpoint()  # must not raise: this thread has no token

        t = threading.Thread(target=other)
        t.start()
        t.join(5)
        assert seen["token"] is None
    finally:
        install_cancel_token(None)


# --------------------------------------------------------------------------
# wire shapes: heartbeat running set, graph deadline, stage failed_on
# --------------------------------------------------------------------------

def test_heartbeat_running_set_is_wire_silent_when_idle():
    idle = serde.executor_heartbeat_to_obj(
        ExecutorHeartbeat("e1", timestamp=1.0))
    assert "running" not in idle, "idle heartbeat must not grow a key"
    busy = ExecutorHeartbeat("e1", timestamp=1.0,
                             running=[("job1", 1, 0, 0), ("job1", 1, 2, 1)])
    back = serde.executor_heartbeat_from_obj(
        serde.executor_heartbeat_to_obj(busy))
    assert back.running == [("job1", 1, 0, 0), ("job1", 1, 2, 1)]


def test_graph_serde_deadline_and_failed_on_roundtrip():
    graph = ExecutionGraph.build("jobd", physical_plan(partitions=3))
    obj = serde.graph_to_obj(graph)
    assert "deadline_ts" not in obj and "deadline_s" not in obj, \
        "deadline-off checkpoints must stay byte-identical to older ones"
    assert all("failed_on" not in st for st in obj["stages"])

    graph.deadline_ts = 1999999999.5
    graph.deadline_s = 42.0
    graph.stages[1].failed_on = {0: {"exec-A", "exec-B"}, 2: {"exec-A"}}
    back = serde.graph_from_obj(serde.graph_to_obj(graph))
    assert back.deadline_ts == 1999999999.5 and back.deadline_s == 42.0
    assert back.stages[1].failed_on == {0: {"exec-A", "exec-B"},
                                        2: {"exec-A"}}
    assert back.stages[2].failed_on == {}


# --------------------------------------------------------------------------
# retry anti-affinity
# --------------------------------------------------------------------------

def test_pop_next_task_steers_retry_off_failing_executor():
    graph = ExecutionGraph.build("joba", physical_plan(partitions=3))
    graph.stages[1].failed_on = {0: {"exec-A"}}
    alive = {"exec-A", "exec-B"}
    taken = []
    while True:
        t = graph.pop_next_task("exec-A", alive=alive)
        if t is None:
            break
        taken.append(t.task.partition)
    assert 0 not in taken, "exec-A already failed partition 0"
    assert sorted(taken) == [1, 2]
    t = graph.pop_next_task("exec-B", alive=alive)
    assert t is not None and t.task.partition == 0


def test_pop_next_task_escape_hatch_single_executor():
    """When the failed-on set covers the alive fleet the steer degrades
    to a plain retry — a one-executor cluster must never deadlock."""
    graph = ExecutionGraph.build("jobb", physical_plan(partitions=3))
    graph.stages[1].failed_on = {0: {"exec-A"}}
    t = graph.pop_next_task("exec-A", alive={"exec-A"})
    assert t is not None and t.task.partition == 0
    # no alive context at all (legacy callers): no veto either
    graph2 = ExecutionGraph.build("jobc", physical_plan(partitions=3))
    graph2.stages[1].failed_on = {0: {"exec-A"}}
    t2 = graph2.pop_next_task("exec-A")
    assert t2 is not None and t2.task.partition == 0


def test_rollback_clears_anti_affinity():
    graph = ExecutionGraph.build("jobr", physical_plan(partitions=3))
    stage = graph.stages[1]
    stage.failed_on = {0: {"exec-A"}}
    while True:
        t = graph.pop_next_task("exec-B", alive={"exec-A", "exec-B"})
        if t is None:
            break
        graph.update_task_status([fake_success(t, "exec-B")])
    stage.rollback()
    assert stage.failed_on == {}


# --------------------------------------------------------------------------
# server-side deadlines
# --------------------------------------------------------------------------

def test_deadline_stamped_from_session_config():
    ctx = _ctx({"ballista.query.deadline.seconds": "120"})
    try:
        before = time.time()
        ctx.sql(SQL).to_pandas()
        sched = ctx._standalone.scheduler
        graph = sched.jobs.get_graph(ctx._standalone.last_job_id)
        assert graph.deadline_s == 120.0
        assert graph.deadline_ts == pytest.approx(before + 120.0, abs=30.0)
    finally:
        ctx._standalone.shutdown()


def test_deadline_override_per_submit():
    ctx = _ctx()  # session default: no deadline
    try:
        ctx.sql(SQL).to_pandas()
        sched = ctx._standalone.scheduler
        assert sched.jobs.get_graph(
            ctx._standalone.last_job_id).deadline_s == 0.0
        # per-submit config override wins over the session default
        override = BallistaConfig({"ballista.shuffle.partitions": "4",
                                   "ballista.query.deadline.seconds": "90"})
        ctx._standalone.execute_sql(
            "select g, min(v) as lo from t group by g order by g",
            ctx.catalog, config=override)
        assert sched.jobs.get_graph(
            ctx._standalone.last_job_id).deadline_s == 90.0
    finally:
        ctx._standalone.shutdown()


def test_deadline_expires_stalled_job_fleet_wide():
    ctx = _ctx({"ballista.query.deadline.seconds": "2.0"})
    try:
        sched = ctx._standalone.scheduler
        t0 = time.monotonic()
        with faults.use_plan(_stall_plan()):
            with pytest.raises(ExecutionError, match="DeadlineExceeded"):
                ctx.sql(SQL).to_pandas()
        # budget 2 s + reaper cadence 1 s, with generous slack
        assert time.monotonic() - t0 < 10.0
        job_id = ctx._standalone.last_job_id
        status = sched.jobs.get_status(job_id)
        assert status.state == "failed" and not status.retriable, \
            "DeadlineExceeded is terminal: clients must not blind-resubmit"
        assert sched.metrics.counters_snapshot()[
            "jobs_deadline_exceeded_total"] == 1
        from arrow_ballista_tpu.obs import journal

        kinds = [e["kind"] for e in journal.job_timeline(job_id)]
        assert "job.deadline_exceeded" in kinds
        _assert_no_leaks(sched, ctx._standalone.executors)
    finally:
        ctx._standalone.shutdown()


def test_generous_deadline_is_invisible():
    """A deadline the query never hits must not change results."""
    plain = _ctx()
    armed = _ctx({"ballista.query.deadline.seconds": "300"})
    try:
        expected = plain.sql(SQL).to_pandas()
        got = armed.sql(SQL).to_pandas()
        assert got.equals(expected)
        assert armed._standalone.scheduler.metrics.counters_snapshot()[
            "jobs_deadline_exceeded_total"] == 0
    finally:
        plain._standalone.shutdown()
        armed._standalone.shutdown()


# --------------------------------------------------------------------------
# public cancel surface
# --------------------------------------------------------------------------

def test_cancel_surface_releases_everything():
    ctx = _ctx()
    try:
        sched = ctx._standalone.scheduler
        result = {}

        def run():
            try:
                ctx.sql(SQL).to_pandas()
                result["out"] = "completed"
            except ExecutionError as e:
                result["out"] = str(e)

        with faults.use_plan(_stall_plan(delay_ms=3000)):
            th = threading.Thread(target=run)
            th.start()
            deadline = time.monotonic() + 10.0
            while (ctx._standalone.last_job_id is None
                   or not any(ex.active_tasks()
                              for ex in ctx._standalone.executors)) \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert any(ex.active_tasks() for ex in ctx._standalone.executors)
            t0 = time.monotonic()
            ctx.cancel()  # defaults to the last submitted job
            th.join(timeout=20.0)
        assert not th.is_alive(), "cancel did not unblock the caller"
        assert time.monotonic() - t0 < 15.0
        assert "cancelled" in result["out"]
        status = sched.jobs.get_status(ctx._standalone.last_job_id)
        assert status.state == "cancelled"
        ctx.cancel()  # idempotent: cancelling a finished job is a no-op
        _assert_no_leaks(sched, ctx._standalone.executors)
        # the session still works after a cancel
        assert len(ctx.sql(SQL).to_pandas()) == 7
    finally:
        ctx._standalone.shutdown()


def test_cancel_without_job_raises():
    ctx = _ctx()
    try:
        with pytest.raises(PlanningError, match="no job"):
            ctx.cancel()
    finally:
        ctx._standalone.shutdown()


def test_cli_cancel_command(capsys):
    from arrow_ballista_tpu.cli import run_command

    ctx = _ctx()
    try:
        ctx.sql(SQL).to_pandas()
        run_command(ctx, r"\cancel", False)
        assert "cancel requested" in capsys.readouterr().out
    finally:
        ctx._standalone.shutdown()


# --------------------------------------------------------------------------
# poison-query containment
# --------------------------------------------------------------------------

def _poison_plan():
    return faults.FaultPlan.from_obj({"seed": 3, "rules": [{
        "site": "executor.task.before_run", "action": "raise", "error": "io",
        "message": "poison split: unreadable block", "times": -1,
        "match": {"stage_id": 1, "partition": 0}}]})


def test_poison_query_fails_fast_and_refunds_quarantine():
    ctx = _ctx()
    try:
        sched = ctx._standalone.scheduler
        with faults.use_plan(_poison_plan()):
            with pytest.raises(ExecutionError, match="PoisonQuery"):
                ctx.sql(SQL).to_pandas()
        job_id = ctx._standalone.last_job_id
        status = sched.jobs.get_status(job_id)
        assert status.state == "failed" and not status.retriable
        assert sched.metrics.counters_snapshot()["jobs_poisoned_total"] == 1
        # the whole point: the query's crime charges NO executor
        snap = sched.quarantine.snapshot()
        assert not snap["quarantined"] and snap["total_quarantined"] == 0
        from arrow_ballista_tpu.obs import journal

        pois = [e for e in journal.job_timeline(job_id)
                if e["kind"] == "job.poisoned"]
        assert pois, "classification must land in the flight record"
        evidence = pois[0]["attrs"]["evidence"]
        (witnesses,) = evidence.values()
        assert len(witnesses) >= 2, \
            "poison needs testimony from K distinct executors"
        # the fleet is intact: the next (healthy) query just runs
        assert len(ctx.sql(SQL).to_pandas()) == 7
        _assert_no_leaks(sched, ctx._standalone.executors)
    finally:
        ctx._standalone.shutdown()


def test_poison_classification_disabled_by_zero():
    ctx = _ctx({"ballista.poison.distinct_executors": "0"})
    try:
        with faults.use_plan(_poison_plan()):
            with pytest.raises(ExecutionError) as exc:
                ctx.sql(SQL).to_pandas()
        # classification off: the plain retry budget decides the failure
        assert "PoisonQuery" not in str(exc.value)
        assert "failed 4 times" in str(exc.value)
    finally:
        ctx._standalone.shutdown()


def test_poison_attaches_forensics_with_doctor_finding():
    ctx = _ctx()
    try:
        sched = ctx._standalone.scheduler
        with faults.use_plan(_poison_plan()):
            with pytest.raises(ExecutionError, match="PoisonQuery"):
                ctx.sql(SQL).to_pandas()
        graph = sched.jobs.get_graph(ctx._standalone.last_job_id)
        deadline = time.monotonic() + 10.0
        while getattr(graph, "forensics", None) is None \
                and time.monotonic() < deadline:
            time.sleep(0.05)  # forensics attach is post-terminal
        assert graph.forensics is not None
        from arrow_ballista_tpu.obs.doctor import diagnose

        findings = diagnose(graph.forensics)["findings"]
        ps = [f for f in findings if f["rule"] == "poison-suspect"]
        assert ps and ps[0]["evidence"]["distinct_executors"] >= 2
    finally:
        ctx._standalone.shutdown()


# --------------------------------------------------------------------------
# zombie-task reconciliation
# --------------------------------------------------------------------------

def test_heartbeat_reaps_tasks_of_closed_jobs():
    server, launcher = scheduler_test()
    try:
        from .test_scheduler import run_job

        status = run_job(server, physical_plan())
        assert status.state == "successful"
        # the executor claims it still runs tasks for the finished job —
        # exactly what a lost cancel/cleanup RPC leaves behind
        server.heartbeat(ExecutorHeartbeat(
            "exec-0", running=[("job1", 2, 0, 0), ("job1", 2, 1, 0)]))
        deadline = time.monotonic() + 10.0
        while not launcher.cancelled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ("exec-0", "job1") in launcher.cancelled
        assert server.metrics.counters_snapshot()[
            "zombie_tasks_reaped_total"] == 2
    finally:
        server.shutdown()


def test_heartbeat_running_live_job_not_reaped():
    server, launcher = scheduler_test()
    try:
        from .test_scheduler import run_job

        run_job(server, physical_plan())
        # unknown-but-checkpointable jobs and live jobs are NOT zombies;
        # tasks of a job this scheduler never heard of ARE (restart case)
        server.heartbeat(ExecutorHeartbeat(
            "exec-1", running=[("never-seen", 1, 0, 0)]))
        deadline = time.monotonic() + 10.0
        while not launcher.cancelled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ("exec-1", "never-seen") in launcher.cancelled
    finally:
        server.shutdown()


def test_janitor_spares_live_job_dirs(tmp_path):
    """The shrunk-TTL regression: a workspace with RUNNING tasks must
    survive the janitor however stale its file mtimes look."""
    import os

    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    sched = SchedulerNetService(
        "127.0.0.1", 0, config=BallistaConfig({}))
    sched.start()
    ex = None
    try:
        ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                            work_dir=str(tmp_path), concurrent_tasks=1,
                            executor_id="janitor-ex",
                            job_data_ttl_s=0.1, janitor_interval_s=0.1)
        ex.start()
        live_dir = tmp_path / "livejob"
        live_dir.mkdir()
        (live_dir / "data-0.arrow").write_bytes(b"x")
        old = time.time() - 3600
        os.utime(live_dir / "data-0.arrow", (old, old))
        os.utime(live_dir, (old, old))
        # registering an in-flight token marks the job live on this host
        ex.executor._inflight[("livejob", 1, 0, 0)] = CancelToken()
        time.sleep(0.8)  # several janitor sweeps past the 0.1 s TTL
        assert live_dir.exists(), \
            "janitor deleted a job with running tasks"
        del ex.executor._inflight[("livejob", 1, 0, 0)]
        deadline = time.monotonic() + 10.0
        while live_dir.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not live_dir.exists(), \
            "janitor must reclaim the dir once the job has no live tasks"
    finally:
        if ex is not None:
            ex.stop(notify=False)
        sched.stop()
