"""Object-store registry: scheme-based FS resolution behind scans.

Parity: reference BallistaObjectStoreRegistry resolves s3/oss/azure/hdfs
URLs per scheme (ballista/core/src/utils.rs:88-174).  The conformance
surface here is a custom scheme served by an fsspec filesystem — the same
plug point S3/GCS use (pyarrow natively), so `register_parquet("s3://...")`
plans and scans through the identical code path.
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from arrow_ballista_tpu.utils import object_store as obs


@pytest.fixture()
def memfs():
    fsspec = pytest.importorskip("fsspec")
    fs = fsspec.filesystem("memory")
    # memory filesystem is process-global: isolate per test
    fs.store.clear()
    obs.register_fsspec("mem", fs)
    yield fs
    fs.store.clear()


def _write_parquet(fs, path, table):
    with fs.open(path, "wb") as f:
        pq.write_table(table, f)


def test_resolve_local(tmp_path):
    fs, p = obs.resolve(str(tmp_path))
    import pyarrow.fs as pafs

    assert isinstance(fs, pafs.LocalFileSystem)
    assert p == str(tmp_path)


def test_list_files_custom_scheme(memfs):
    t = pa.table({"x": [1, 2, 3]})
    _write_parquet(memfs, "/data/a.parquet", t)
    _write_parquet(memfs, "/data/b.parquet", t)
    memfs.pipe_file("/data/ignore.txt", b"hi")
    files = obs.list_files("mem://data", (".parquet",))
    assert [f.split("/")[-1] for f in files] == ["a.parquet", "b.parquet"]
    assert all(f.startswith("mem://") for f in files)


def test_register_parquet_scans_object_store(memfs):
    rng = np.random.default_rng(5)
    n = 5_000
    t = pa.table({
        "k": pa.array(rng.integers(0, 4, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 1000, n).astype(np.int64)),
    })
    _write_parquet(memfs, "/tbl/part-0.parquet", t.slice(0, n // 2))
    _write_parquet(memfs, "/tbl/part-1.parquet", t.slice(n // 2))

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    try:
        ctx.register_parquet("t", "mem://tbl")
        got = ctx.sql(
            "SELECT k, SUM(v) AS s, COUNT(*) AS c FROM t GROUP BY k ORDER BY k"
        ).to_pandas()
    finally:
        ctx.shutdown()

    df = t.to_pandas()
    want = (df.groupby("k", as_index=False)
            .agg(s=("v", "sum"), c=("v", "size"))
            .sort_values("k").reset_index(drop=True))
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_row_group_pruning_on_object_store(memfs):
    # statistics-based pruning must work through the registry too
    t1 = pa.table({"x": pa.array(np.arange(0, 100, dtype=np.int64))})
    t2 = pa.table({"x": pa.array(np.arange(1000, 1100, dtype=np.int64))})
    _write_parquet(memfs, "/pr/a.parquet", t1)
    _write_parquet(memfs, "/pr/b.parquet", t2)

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    try:
        ctx.register_parquet("p", "mem://pr")
        got = ctx.sql("SELECT COUNT(*) AS c FROM p WHERE x >= 1000").to_pandas()
        assert got["c"].tolist() == [100]
    finally:
        ctx.shutdown()


def test_unknown_scheme_fails_cleanly():
    from arrow_ballista_tpu.utils.errors import ExecutionError

    with pytest.raises(ExecutionError, match="no object store registered"):
        obs.resolve("definitelynotascheme123://x/y")
