"""Scheduler control-plane tests: virtual-executor cluster in one process.

Mirrors the reference's three test seams (SURVEY.md §4, reference
ballista/scheduler/src/test_utils.rs):

1. ``VirtualTaskLauncher`` — synchronously fabricates TaskStatus results
   (incl. fake shuffle paths) and feeds them back through
   ``update_task_status``: a full cluster, no I/O, no executors.
2. ``SchedulerTest``-style harness — parameterized executors/slots with a
   per-task outcome hook for failure injection.
3. ExecutionGraph drain simulation — mock task completions pump the graph
   to completion in-process (reference execution_graph.rs test mod).
"""
import threading
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu.catalog import MemoryTable, SchemaCatalog
from arrow_ballista_tpu.ops.shuffle import ShuffleWritePartition
from arrow_ballista_tpu.scheduler.execution_graph import (
    RUNNING,
    STAGE_MAX_FAILURES,
    SUCCESSFUL,
    TASK_MAX_FAILURES,
    UNRESOLVED,
    ExecutionGraph,
)
from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner
from arrow_ballista_tpu.scheduler.scheduler import (
    SchedulerConfig,
    SchedulerServer,
    TaskLauncher,
)
from arrow_ballista_tpu.scheduler.types import (
    EXECUTION_ERROR,
    FETCH_PARTITION_ERROR,
    IO_ERROR,
    ExecutorMetadata,
    FailedReason,
    TaskDescription,
    TaskStatus,
)
from arrow_ballista_tpu.sql.optimizer import optimize
from arrow_ballista_tpu.sql.parser import parse_sql
from arrow_ballista_tpu.sql.planner import SqlToRel
from arrow_ballista_tpu.utils.config import BallistaConfig


# --------------------------------------------------------------------------
# plan fixture: a 2-stage aggregation + sort over a tiny in-memory table
# --------------------------------------------------------------------------

def physical_plan(sql: str = None, partitions: int = 4):
    rng = np.random.default_rng(0)
    t = pa.table({
        "k": pa.array(rng.integers(0, 5, 1000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 1000).astype(np.int64)),
    })
    catalog = SchemaCatalog()
    catalog.register(MemoryTable("t", t))
    config = BallistaConfig({"ballista.shuffle.partitions": str(partitions)})
    sql = sql or "select k, sum(v) as s from t group by k order by k"
    logical = optimize(SqlToRel(catalog).plan(parse_sql(sql)))
    return PhysicalPlanner(catalog, config).plan_query(logical).plan


def fake_success(task: TaskDescription, executor_id: str) -> TaskStatus:
    """Fabricate a successful status with fake shuffle files (parity:
    reference test_utils.rs VirtualExecutor mock_completed_task)."""
    writer = task.plan
    if writer.partitioning is None:
        writes = [ShuffleWritePartition(task.task.partition,
                                        f"/fake/{task.task.job_id}/{task.task.stage_id}"
                                        f"/{task.task.partition}/data-0.arrow", 10, 100)]
    else:
        writes = [ShuffleWritePartition(q, f"/fake/{task.task.job_id}"
                                        f"/{task.task.stage_id}/{task.task.partition}"
                                        f"/data-{q}.arrow", 10, 100)
                  for q in range(writer.partitioning.count)]
    return TaskStatus(task.task, executor_id, "success", shuffle_writes=writes)


class VirtualTaskLauncher(TaskLauncher):
    """Synchronous virtual cluster: every launched task completes (or
    fails, per ``outcome_fn``) immediately, looping status back into the
    scheduler (reference test_utils.rs:313-372)."""

    def __init__(self, outcome_fn: Optional[Callable] = None):
        self.scheduler: Optional[SchedulerServer] = None
        self.outcome_fn = outcome_fn  # (task, executor_id) -> TaskStatus|None
        self.launched: List[Tuple[str, TaskDescription]] = []
        self.cancelled: List[Tuple[str, str]] = []
        self._lock = threading.Lock()

    def launch_tasks(self, executor_id, tasks):
        statuses = []
        with self._lock:
            for t in tasks:
                self.launched.append((executor_id, t))
        for t in tasks:
            st = None
            if self.outcome_fn is not None:
                st = self.outcome_fn(t, executor_id)
            statuses.append(st or fake_success(t, executor_id))
        self.scheduler.update_task_status(executor_id, statuses)

    def cancel_tasks(self, executor_id, job_id):
        self.cancelled.append((executor_id, job_id))


class BlackholeTaskLauncher(TaskLauncher):
    """Drops tasks on the floor (reference test_utils.rs:327-339)."""

    def __init__(self):
        self.count = 0

    def launch_tasks(self, executor_id, tasks):
        self.count += len(tasks)


def scheduler_test(n_executors=2, slots=4, outcome_fn=None, launcher=None):
    """SchedulerTest harness (reference test_utils.rs:375-672)."""
    launcher = launcher or VirtualTaskLauncher(outcome_fn)
    server = SchedulerServer(launcher, SchedulerConfig())
    if hasattr(launcher, "scheduler"):
        launcher.scheduler = server
    server.init(start_reaper=False)
    for i in range(n_executors):
        server.register_executor(
            ExecutorMetadata(executor_id=f"exec-{i}", task_slots=slots))
    return server, launcher


def run_job(server, plan, job_id="job1", timeout=30.0):
    server.submit_job(job_id, lambda: (plan, {}))
    return server.wait_for_job(job_id, timeout)


# --------------------------------------------------------------------------
# happy path
# --------------------------------------------------------------------------

def test_virtual_cluster_job_success():
    server, launcher = scheduler_test()
    status = run_job(server, physical_plan())
    assert status.state == "successful"
    assert status.locations, "final stage locations must be reported"
    # every launched task had a resolved (executable) plan
    for _, task in launcher.launched:
        assert task.plan is not None
    server.shutdown()


def test_tasks_spread_over_executors_round_robin():
    launcher = VirtualTaskLauncher()
    server = SchedulerServer(launcher, SchedulerConfig(task_distribution="round-robin"))
    launcher.scheduler = server
    server.init(start_reaper=False)
    for i in range(4):
        server.register_executor(ExecutorMetadata(f"exec-{i}", task_slots=8))
    status = run_job(server, physical_plan())
    assert status.state == "successful"
    used = {eid for eid, _ in launcher.launched}
    assert len(used) >= 2, f"round-robin should spread tasks, used {used}"
    server.shutdown()


def test_job_queued_until_executor_registers():
    launcher = VirtualTaskLauncher()
    server = SchedulerServer(launcher, SchedulerConfig())
    launcher.scheduler = server
    server.init(start_reaper=False)
    server.submit_job("job1", lambda: (physical_plan(), {}))
    # no executors: job must stay running with pending tasks once planned
    # (planning is async — poll for the graph)
    import time as _t

    deadline = _t.monotonic() + 10
    while server.pending_task_count() == 0 and _t.monotonic() < deadline:
        _t.sleep(0.01)
    assert server.get_job_status("job1").state == "running"
    assert server.pending_task_count() > 0
    server.register_executor(ExecutorMetadata("exec-0", task_slots=4))
    assert server.wait_for_job("job1", 30).state == "successful"
    server.shutdown()


def test_event_handler_crash_fails_job():
    """A crash INSIDE an event handler must fail the affected job, not
    strand it in 'running' until the deadline (EventLoop on_error hook)."""
    server, _ = scheduler_test()

    def exploding_handler(ev):
        raise RuntimeError("handler exploded")

    server._on_job_planned = exploding_handler
    server.submit_job("boom2", lambda: (physical_plan(), {}))
    status = server.wait_for_job("boom2", 10)
    assert status.state == "failed"
    assert "handler exploded" in status.error
    server.shutdown()


def test_task_updating_handler_crash_fails_job():
    """TaskUpdating events carry no job_id field — the on_error hook must
    recover the affected jobs from the statuses' task ids, and stop the
    graph so no late event resurrects the job."""
    server, _ = scheduler_test()

    def exploding_handler(ev):
        raise RuntimeError("status intake exploded")

    server._on_task_updating = exploding_handler
    server.submit_job("boom3", lambda: (physical_plan(), {}))
    status = server.wait_for_job("boom3", 10)
    assert status.state == "failed"
    assert "status intake exploded" in status.error
    graph = server.jobs.get_graph("boom3")
    assert graph is not None and graph.status == "failed"
    server.shutdown()


def test_planning_failure_fails_job():
    def exploding_plan():
        raise RuntimeError("ExplodingTableProvider")  # test_utils.rs:71-103

    server, _ = scheduler_test()
    server.submit_job("boom", exploding_plan)
    status = server.wait_for_job("boom", 10)
    assert status.state == "failed"
    assert "ExplodingTableProvider" in status.error
    server.shutdown()


# --------------------------------------------------------------------------
# failure handling through the full scheduler
# --------------------------------------------------------------------------

def test_retryable_failure_then_success():
    failed_once: Dict[tuple, bool] = {}

    def outcome(task, executor_id):
        key = (task.task.stage_id, task.task.partition)
        if task.task.stage_id == 1 and task.task.partition == 0 \
                and not failed_once.get(key):
            failed_once[key] = True
            return TaskStatus(task.task, executor_id, "failed",
                              failure=FailedReason(IO_ERROR, "flaky disk"))
        return None

    server, launcher = scheduler_test(outcome_fn=outcome)
    status = run_job(server, physical_plan())
    assert status.state == "successful"
    assert failed_once, "the injected failure must have fired"
    server.shutdown()


def test_execution_error_fails_job():
    def outcome(task, executor_id):
        return TaskStatus(task.task, executor_id, "failed",
                          failure=FailedReason(EXECUTION_ERROR, "div by zero"))

    server, _ = scheduler_test(outcome_fn=outcome)
    status = run_job(server, physical_plan())
    assert status.state == "failed"
    assert "div by zero" in status.error
    server.shutdown()


def test_task_retries_exhausted_fails_job():
    def outcome(task, executor_id):
        if task.task.stage_id == 1 and task.task.partition == 0:
            return TaskStatus(task.task, executor_id, "failed",
                              failure=FailedReason(IO_ERROR, "always broken"))
        return None

    # poison classification off: this test is about the plain retry
    # budget (the 2-distinct-executor classifier would otherwise fail the
    # job as PoisonQuery on the second attempt — tests/test_lifecycle.py
    # covers that path)
    server, _ = scheduler_test(outcome_fn=outcome)
    server.config.poison_distinct_executors = 0
    status = run_job(server, physical_plan())
    assert status.state == "failed"
    assert "4 times" in status.error
    server.shutdown()


def test_fetch_failure_triggers_producer_rerun():
    reran_map: List[int] = []
    injected = threading.Event()

    def outcome(task, executor_id):
        tid = task.task
        # final stage tasks: first one reports it couldn't fetch map
        # partition 2 of stage 1
        if tid.stage_id == 2 and not injected.is_set():
            injected.set()
            return TaskStatus(tid, executor_id, "failed",
                              failure=FailedReason(
                                  FETCH_PARTITION_ERROR, "connection reset",
                                  map_stage_id=1, map_partition_id=2,
                                  executor_id=executor_id))
        if tid.stage_id == 1 and injected.is_set():
            reran_map.append(tid.partition)
        return None

    server, launcher = scheduler_test(outcome_fn=outcome)
    status = run_job(server, physical_plan())
    assert status.state == "successful"
    assert injected.is_set()
    assert 2 in reran_map, f"map partition 2 must re-run, got {reran_map}"
    server.shutdown()


def test_job_cancel():
    launcher = BlackholeTaskLauncher()
    server = SchedulerServer(launcher, SchedulerConfig())
    server.init(start_reaper=False)
    server.register_executor(ExecutorMetadata("exec-0", task_slots=4))
    server.submit_job("job1", lambda: (physical_plan(), {}))
    import time as _t

    deadline = _t.monotonic() + 10
    while launcher.count == 0 and _t.monotonic() < deadline:
        _t.sleep(0.01)
    assert launcher.count > 0, "tasks must have been launched (and dropped)"
    server.cancel_job("job1")
    status = server.wait_for_job("job1", 10)
    assert status.state == "cancelled"
    server.shutdown()


# --------------------------------------------------------------------------
# ExecutionGraph drain simulation (no scheduler, no launcher)
# --------------------------------------------------------------------------

def drain(graph: ExecutionGraph, executor_id="exec-0", hook=None):
    """Pump the graph with fabricated completions (reference
    execution_graph.rs drain_tasks test helper)."""
    events = []
    for _ in range(10000):
        task = graph.pop_next_task(executor_id)
        if task is None:
            if graph.status != "running":
                break
            # nothing runnable but job alive -> deadlock in the graph
            raise AssertionError(f"graph stalled: {graph!r}")
        st = hook(task) if hook else None
        events.extend(graph.update_task_status([st or fake_success(task, executor_id)]))
    return events


def test_graph_stage_structure():
    graph = ExecutionGraph.build("j", physical_plan(partitions=4))
    # agg: partial (stage 1) -> final agg + sort-to-one (stage 2) -> final (stage 3)
    assert len(graph.stages) == 3
    s1, s2, s3 = (graph.stages[i] for i in (1, 2, 3))
    assert s1.state == RUNNING and s2.state == UNRESOLVED and s3.state == UNRESOLVED
    assert s1.output_links == [2] and s2.output_links == [3]
    assert s2.producer_ids == [1] and s3.producer_ids == [2]
    assert graph.final_stage_id == 3


def test_graph_drain_to_success():
    graph = ExecutionGraph.build("j", physical_plan())
    events = drain(graph)
    assert graph.status == "successful"
    assert events and events[-1][0] == "job_successful"
    locations = events[-1][1]
    assert sorted(locations) == [0]  # single final partition (sort)


def test_graph_executor_lost_mid_flight():
    graph = ExecutionGraph.build("j", physical_plan(partitions=4))
    # run stage 1 fully on exec-A
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("exec-A")
        graph.update_task_status([fake_success(t, "exec-A")])
    assert graph.stages[1].state == SUCCESSFUL
    assert graph.stages[2].state == RUNNING
    # start one stage-2 task on exec-B, then lose exec-A (all stage-1 outputs)
    t2 = graph.pop_next_task("exec-B")
    graph.executor_lost("exec-A")
    assert graph.stages[1].state == RUNNING, "stage 1 outputs lost -> re-run"
    assert graph.stages[2].state == UNRESOLVED, "stage 2 must roll back"
    # graph still completes, now on exec-B
    drain(graph, "exec-B")
    assert graph.status == "successful"


def test_graph_reresolve_uses_fresh_locations():
    """After a rollback, re-resolution must see the re-run producer's NEW
    locations, not the dead attempt's (regression: resolve mutates the
    stage plan in place; rollback must restore the unresolved leaves)."""
    from arrow_ballista_tpu.ops.shuffle import ShuffleReaderExec
    from arrow_ballista_tpu.scheduler.planner import collect_nodes

    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("exec-A")
        graph.update_task_status([fake_success(t, "exec-A")])
    assert graph.stages[2].state == RUNNING
    graph.executor_lost("exec-A")  # all stage-1 outputs gone
    assert graph.stages[2].state == UNRESOLVED
    # stage 1 re-runs on exec-B
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("exec-B")
        graph.update_task_status([fake_success(t, "exec-B")])
    assert graph.stages[2].state == RUNNING
    readers = collect_nodes(graph.stages[2].resolved_plan, ShuffleReaderExec)
    assert readers, "stage 2 must have re-resolved shuffle readers"
    for r in readers:
        for locs in r.locations.values():
            for loc in locs:
                assert loc.executor_id == "exec-B", \
                    f"stale location from dead executor: {loc}"
    drain(graph, "exec-B")
    assert graph.status == "successful"


def test_graph_fetch_failure_attempt_budget():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    # stage 1 completes
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("e")
        graph.update_task_status([fake_success(t, "e")])

    # every stage-2 attempt immediately reports a fetch failure
    failures = 0
    events = []
    for _ in range(20):
        t = graph.pop_next_task("e")
        if t is None:
            break
        if t.task.stage_id != 2:
            events.extend(graph.update_task_status([fake_success(t, "e")]))
            continue
        failures += 1
        events.extend(graph.update_task_status([TaskStatus(
            t.task, "e", "failed",
            failure=FailedReason(FETCH_PARTITION_ERROR, "dead peer",
                                 map_stage_id=1, map_partition_id=0,
                                 executor_id="e"))]))
    assert graph.status == "failed"
    assert failures <= STAGE_MAX_FAILURES
    assert any(k == "job_failed" for k, _ in events)


def test_graph_fetch_budget_exhaustion_preserves_cause():
    """When the fetch-failure budget runs out, the job error must carry the
    ORIGINAL fetch failure message — not just the budget arithmetic — or the
    operator debugging a dead job loses the root cause."""
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("e")
        graph.update_task_status([fake_success(t, "e")])
    for _ in range(20):
        t = graph.pop_next_task("e")
        if t is None:
            break
        if t.task.stage_id != 2:
            graph.update_task_status([fake_success(t, "e")])
            continue
        graph.update_task_status([TaskStatus(
            t.task, "e", "failed",
            failure=FailedReason(FETCH_PARTITION_ERROR, "dead peer at 10.0.0.9",
                                 map_stage_id=1, map_partition_id=0,
                                 executor_id="e"))])
    assert graph.status == "failed"
    assert "dead peer at 10.0.0.9" in graph.error, \
        f"budget message must keep the root cause, got: {graph.error}"


def test_graph_executor_lost_charges_no_budgets():
    """Executor loss is not the query's fault: the rollback/reopen it forces
    must not consume stage or task retry budgets, and the poisoned consumer's
    in-flight tasks must be fully reset (regression guard for the chaos
    executor-kill scenario)."""
    graph = ExecutionGraph.build("j", physical_plan(partitions=4))
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("exec-A")
        graph.update_task_status([fake_success(t, "exec-A")])
    assert graph.stages[2].state == RUNNING
    t2 = graph.pop_next_task("exec-B")
    assert t2 is not None and t2.task.stage_id == 2

    graph.executor_lost("exec-A")
    # stage budgets untouched (rollback/reopen with count_failure=False)
    assert all(s.failures == 0 for s in graph.stages.values())
    # per-task budgets untouched
    assert all(f == 0 for s in graph.stages.values() for f in s.task_failures)
    # the poisoned consumer is fully reset: no stale in-flight slots
    assert graph.stages[2].state == UNRESOLVED
    assert all(i is None for i in graph.stages[2].task_infos)
    # but epochs advanced, so late statuses from the dead attempt are stale
    assert graph.stages[1].stage_attempt >= 1
    # and the graph still drains to success on the survivor, with full
    # budgets available for real failures later
    drain(graph, "exec-B")
    assert graph.status == "successful"


def test_graph_duplicate_success_ignored():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("e")
    st = fake_success(t, "e")
    graph.update_task_status([st])
    before = dict(graph.stages[t.task.stage_id].outputs)
    graph.update_task_status([st])  # duplicate report
    assert graph.stages[t.task.stage_id].outputs == before


def test_graph_late_status_from_old_attempt_dropped():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    while graph.stages[1].pending_partitions():
        t = graph.pop_next_task("e")
        graph.update_task_status([fake_success(t, "e")])
    t2 = graph.pop_next_task("e")
    assert t2.task.stage_id == 2
    # fetch failure rolls stage 2 back; its attempt counter bumps
    graph.update_task_status([TaskStatus(
        t2.task, "e", "failed",
        failure=FailedReason(FETCH_PARTITION_ERROR, "x", map_stage_id=1,
                             map_partition_id=0, executor_id="e"))])
    # a late success from the old attempt must be ignored
    graph.update_task_status([fake_success(t2, "e")])
    stage2 = graph.stages[2]
    assert stage2.state == UNRESOLVED
    assert all(i is None for i in stage2.task_infos)


def test_adaptive_exchange_coalescing():
    """A reduce stage whose real shuffle input is tiny collapses to ONE
    task at resolve time (the planner asked for N; the scheduler knows the
    actual producer output sizes — q1's 46-task final stage over 48 rows
    was pure overhead)."""
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.config import BallistaConfig

    ctx = BallistaContext.standalone(BallistaConfig({
        "ballista.shuffle.partitions": "16"}), concurrent_tasks=2)
    rng = np.random.default_rng(2)
    ctx.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 4, 20_000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 20_000).astype(np.int64))}))
    out = ctx.sql("select g, sum(v) s from t group by g order by g").to_pandas()
    assert len(out) == 4

    sched = ctx._standalone.scheduler
    job_id = list(sched.jobs._status)[-1]
    graph = sched.jobs.get_graph(job_id)
    # the final-aggregate stage consumed a 4-row-ish shuffle: must have
    # run as ONE task despite the 16-way hash partitioning
    coalesced = [s for s in graph.stages.values()
                 if s.planned_partitions != s.partitions]
    assert coalesced, "no stage was coalesced"
    assert all(s.partitions == 1 and len(s.task_infos) == 1
               for s in coalesced)
    ctx.shutdown()
