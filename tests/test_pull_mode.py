"""Pull-mode scheduling: executors poll for work (reference PollWork,
grpc.rs:57-136 + execution_loop.rs poll loop)."""
import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(scope="module")
def pull_cluster(tmp_path_factory):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService
    from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig

    sched = SchedulerNetService(
        "127.0.0.1", 0,
        config=BallistaConfig({"ballista.shuffle.partitions": "4"}),
        scheduler_config=SchedulerConfig(policy="pull"))
    sched.start()
    executors = []
    for i in range(2):
        ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                            work_dir=str(tmp_path_factory.mktemp(f"pull{i}")),
                            executor_id=f"pull-exec-{i}", policy="pull")
        ex.start()
        executors.append(ex)
    yield sched, executors
    for ex in executors:
        ex.stop(notify=False)
    sched.stop()


def test_pull_mode_query(pull_cluster):
    sched, executors = pull_cluster
    ctx = BallistaContext.remote("127.0.0.1", sched.port)
    rng = np.random.default_rng(5)
    n = 8000
    ctx.register_table("t", pa.table({
        "k": pa.array(rng.integers(0, 9, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    }))
    got = ctx.sql("select k, sum(v) as s, count(*) as c from t "
                  "group by k order by k").to_pandas()
    assert len(got) == 9
    assert int(got.c.sum()) == n


def test_pull_mode_consecutive_jobs(pull_cluster):
    sched, _ = pull_cluster
    ctx = BallistaContext.remote("127.0.0.1", sched.port)
    ctx.register_table("u", pa.table({"x": pa.array(range(100), type=pa.int64())}))
    for _ in range(3):
        out = ctx.sql("select sum(x) as s from u").to_pandas()
        assert int(out.s[0]) == 4950
