"""Scalar UDF plugin system (reference plugin/mod.rs + plugin/udf.rs).

Covers: registry resolution in SQL, device evaluation inside the fused
stage program, serde round-trip (executors resolve by name), and plugin-dir
loading (the dlopen-walk analog).
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu.models.schema import FLOAT64, INT64
from arrow_ballista_tpu.udf import (
    GLOBAL_UDFS,
    load_plugin_dir,
    register_udf,
)


@pytest.fixture()
def udfs():
    names = []

    def reg(name, *a, **kw):
        names.append(name)
        return register_udf(name, *a, **kw)

    yield reg
    for n in names:
        GLOBAL_UDFS.deregister(n)


@pytest.fixture()
def table():
    rng = np.random.default_rng(3)
    n = 2_000
    return pa.table({
        "k": pa.array(rng.integers(0, 5, n).astype(np.int64)),
        "v": pa.array(rng.integers(1, 100, n).astype(np.int64)),
    })


def test_udf_in_sql_local(udfs, table):
    udfs("sq", lambda x: x * x, INT64, arg_count=1)

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    try:
        ctx.register_table("t", table)
        got = ctx.sql("SELECT k, SUM(sq(v)) AS s FROM t GROUP BY k ORDER BY k").to_pandas()
    finally:
        ctx.shutdown()
    df = table.to_pandas()
    df["sq"] = df["v"] ** 2
    want = df.groupby("k", as_index=False).agg(s=("sq", "sum"))
    assert got["s"].tolist() == want["s"].tolist()


def test_udf_through_standalone_cluster(udfs, table):
    # serde path: the plan crosses the scheduler; executors resolve by name
    udfs("plus_ten", lambda x: x + 10, INT64, arg_count=1)

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.standalone(num_executors=2)
    try:
        ctx.register_table("t", table)
        got = ctx.sql("SELECT SUM(plus_ten(v)) AS s FROM t").to_pandas()
    finally:
        ctx.shutdown()
    want = int((table.to_pandas()["v"] + 10).sum())
    assert got["s"].tolist() == [want]


def test_udf_two_args_and_filter(udfs, table):
    udfs("absdiff", lambda x, y: abs(x - y), INT64, arg_count=2)

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    try:
        ctx.register_table("t", table)
        got = ctx.sql(
            "SELECT COUNT(*) AS c FROM t WHERE absdiff(v, 50) <= 10"
        ).to_pandas()
    finally:
        ctx.shutdown()
    df = table.to_pandas()
    want = int(((df["v"] - 50).abs() <= 10).sum())
    assert got["c"].tolist() == [want]


def test_udf_serde_roundtrip(udfs):
    udfs("tri", lambda x: x * (x + 1) // 2, INT64, arg_count=1)
    from arrow_ballista_tpu import serde
    from arrow_ballista_tpu.models import expr as E

    e = E.Udf("tri", (E.Column("v"),))
    rt = serde.expr_from_obj(serde.expr_to_obj(e))
    assert isinstance(rt, E.Udf) and rt.name == "tri"
    assert isinstance(rt.args[0], E.Column)


def test_unknown_function_still_errors(table):
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.errors import PlanningError

    ctx = BallistaContext.local()
    try:
        ctx.register_table("t", table)
        with pytest.raises(PlanningError, match="unsupported function"):
            ctx.sql("SELECT nosuchfn(v) FROM t")
    finally:
        ctx.shutdown()


def test_plugin_dir_loading(tmp_path, table):
    (tmp_path / "myfns.py").write_text(
        "from arrow_ballista_tpu.udf import register_udf\n"
        "from arrow_ballista_tpu.models.schema import FLOAT64\n"
        "register_udf('halve', lambda x: x / 2.0, FLOAT64, arg_count=1)\n"
    )
    loaded = load_plugin_dir(str(tmp_path))
    try:
        assert loaded and GLOBAL_UDFS.get("halve") is not None

        from arrow_ballista_tpu.client.context import BallistaContext

        ctx = BallistaContext.local()
        try:
            ctx.register_table("t", table)
            got = ctx.sql("SELECT SUM(halve(v)) AS s FROM t").to_pandas()
        finally:
            ctx.shutdown()
        want = float((table.to_pandas()["v"] / 2.0).sum())
        assert got["s"].iloc[0] == pytest.approx(want)
    finally:
        GLOBAL_UDFS.deregister("halve")


def test_udf_inside_mesh_fused_aggregate(udfs, table):
    """UDF operands compile into the mesh-fused aggregate program (the
    derive stage runs inside shard_map), and results match the file path."""
    udfs("sq", lambda x: x * x, INT64, arg_count=1)

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.ops.mesh_exec import MeshAggregateExec
    from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner
    from arrow_ballista_tpu.scheduler.planner import collect_nodes
    from arrow_ballista_tpu.sql.optimizer import optimize
    from arrow_ballista_tpu.utils.config import BallistaConfig

    sql = "SELECT k, SUM(sq(v)) AS s FROM t GROUP BY k ORDER BY k"
    mesh_ctx = BallistaContext.local(BallistaConfig({"ballista.shuffle.mesh": "true",
        "ballista.shuffle.mesh.min_rows": "0"}))
    file_ctx = BallistaContext.local()
    try:
        for c in (mesh_ctx, file_ctx):
            c.register_table("t", table)
        df = mesh_ctx.sql(sql)
        planned = PhysicalPlanner(mesh_ctx.catalog, mesh_ctx.config).plan_query(
            optimize(df.logical))
        assert collect_nodes(planned.plan, MeshAggregateExec), \
            f"UDF operand fell off the mesh path:\n{planned.plan.display()}"
        got = df.to_pandas()
        want = file_ctx.sql(sql).to_pandas()
        pd.testing.assert_frame_equal(got, want, check_dtype=False)
    finally:
        mesh_ctx.shutdown()
        file_ctx.shutdown()


def test_udf_replacement_invalidates_shared_programs(tmp_path):
    """Re-registering a UDF must not serve a stale compiled closure from
    the cross-job program cache (exprs_sig carries the registry
    generation)."""
    import numpy as np

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.models.schema import INT64
    from arrow_ballista_tpu.udf import register_udf
    from arrow_ballista_tpu.utils.config import BallistaConfig

    import pyarrow as pa

    ctx = BallistaContext.local(BallistaConfig({}))
    table = pa.table({"x": pa.array([1, 2, 3], type=pa.int64())})
    ctx.register_table("t", table)
    register_udf("bump2", lambda x: x + 1, INT64, arg_count=1)
    r1 = ctx.sql("SELECT bump2(x) AS y FROM t").to_pandas()
    assert list(r1["y"]) == [2, 3, 4]
    register_udf("bump2", lambda x: x * 10, INT64, arg_count=1)
    r2 = ctx.sql("SELECT bump2(x) AS y FROM t").to_pandas()
    assert list(r2["y"]) == [10, 20, 30]
