"""Native data-plane server: build, serve, guard, interop with wire.py."""
import os

import pytest

from arrow_ballista_tpu import native
from arrow_ballista_tpu.net import wire
from arrow_ballista_tpu.net.wire import RemoteError


@pytest.fixture(scope="module")
def dp(tmp_path_factory):
    lib = native.dataplane()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    work = tmp_path_factory.mktemp("dpwork")
    (work / "job1" / "1" / "0").mkdir(parents=True)
    payload = b"arrow-ipc-bytes" * 1000
    (work / "job1" / "1" / "0" / "data-0.arrow").write_bytes(payload)
    port = lib.dp_start(str(work).encode(), 0, b"", 0)
    assert port > 0
    yield lib, str(work), port, payload
    lib.dp_stop()


def test_native_ping(dp):
    _, _, port, _ = dp
    payload, _ = wire.call("127.0.0.1", port, "ping")
    assert payload.get("native") is True


def test_native_fetch(dp):
    _, work, port, payload = dp
    path = os.path.join(work, "job1", "1", "0", "data-0.arrow")
    resp, data = wire.call("127.0.0.1", port, "fetch_partition", {"path": path})
    assert data == payload
    assert resp["num_bytes"] == len(payload)


def test_native_path_traversal_guard(dp):
    _, work, port, _ = dp
    for bad in [os.path.join(work, "..", "etc", "passwd"), "/etc/passwd",
                work]:  # the work dir itself is not a file under it
        with pytest.raises(RemoteError):
            wire.call("127.0.0.1", port, "fetch_partition", {"path": bad})


def test_native_missing_file(dp):
    _, work, port, _ = dp
    with pytest.raises(RemoteError):
        wire.call("127.0.0.1", port, "fetch_partition",
                  {"path": os.path.join(work, "job1", "1", "0", "nope.arrow")})


def test_native_bytes_served_counter(dp):
    lib, work, port, payload = dp
    before = lib.dp_bytes_served()
    path = os.path.join(work, "job1", "1", "0", "data-0.arrow")
    wire.call("127.0.0.1", port, "fetch_partition", {"path": path})
    assert lib.dp_bytes_served() >= before + len(payload)
