"""Native data-plane server: build, serve, guard, interop with wire.py."""
import os

import pytest

from arrow_ballista_tpu import native
from arrow_ballista_tpu.net import wire
from arrow_ballista_tpu.net.wire import RemoteError


@pytest.fixture(scope="module")
def dp(tmp_path_factory):
    lib = native.dataplane()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    work = tmp_path_factory.mktemp("dpwork")
    (work / "job1" / "1" / "0").mkdir(parents=True)
    payload = b"arrow-ipc-bytes" * 1000
    (work / "job1" / "1" / "0" / "data-0.arrow").write_bytes(payload)
    port = lib.dp_start(str(work).encode(), 0, b"", 0)
    assert port > 0
    yield lib, str(work), port, payload
    lib.dp_stop()


def test_native_ping(dp):
    _, _, port, _ = dp
    payload, _ = wire.call("127.0.0.1", port, "ping")
    assert payload.get("native") is True


def test_native_fetch(dp):
    _, work, port, payload = dp
    path = os.path.join(work, "job1", "1", "0", "data-0.arrow")
    resp, data = wire.call("127.0.0.1", port, "fetch_partition", {"path": path})
    assert data == payload
    assert resp["num_bytes"] == len(payload)


def test_native_path_traversal_guard(dp):
    _, work, port, _ = dp
    for bad in [os.path.join(work, "..", "etc", "passwd"), "/etc/passwd",
                work]:  # the work dir itself is not a file under it
        with pytest.raises(RemoteError):
            wire.call("127.0.0.1", port, "fetch_partition", {"path": bad})


def test_native_missing_file(dp):
    _, work, port, _ = dp
    with pytest.raises(RemoteError):
        wire.call("127.0.0.1", port, "fetch_partition",
                  {"path": os.path.join(work, "job1", "1", "0", "nope.arrow")})


def test_native_bytes_served_counter(dp):
    lib, work, port, payload = dp
    before = lib.dp_bytes_served()
    path = os.path.join(work, "job1", "1", "0", "data-0.arrow")
    wire.call("127.0.0.1", port, "fetch_partition", {"path": path})
    assert lib.dp_bytes_served() >= before + len(payload)


def test_native_tsan_concurrent_fetch(tmp_path):
    """Race coverage (SURVEY §5): hammer the TSAN build of the data plane
    with concurrent fetches in a subprocess; any ThreadSanitizer report
    fails the test.  Skipped when the sanitizer toolchain is absent."""
    import subprocess
    import sys

    gcc = subprocess.run(["g++", "-print-file-name=libtsan.so"],
                         capture_output=True, text=True)
    libtsan = gcc.stdout.strip()
    if gcc.returncode != 0 or "/" not in libtsan:
        pytest.skip("libtsan unavailable")
    build = subprocess.run(["make", "-C", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"),
        "sanitize"], capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"sanitize build failed: {build.stderr[-500:]}")

    work = tmp_path / "w"
    (work / "j" / "1" / "0").mkdir(parents=True)
    (work / "j" / "1" / "0" / "data-0.arrow").write_bytes(b"x" * 65536)
    driver = r"""
import ctypes, os, sys, threading
sys.path.insert(0, {repo!r})
from arrow_ballista_tpu.net import wire
lib = ctypes.CDLL({so!r})
lib.dp_start.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
lib.dp_start.restype = ctypes.c_int
port = lib.dp_start({work!r}.encode(), 0, b"tok", 8)
assert port > 0
path = os.path.join({work!r}, "j", "1", "0", "data-0.arrow")
errs = []
def hammer():
    for _ in range(25):
        try:
            _, data = wire.call("127.0.0.1", port, "fetch_partition",
                                {{"path": path, "token": "tok"}})
            assert len(data) == 65536
        except Exception as e:
            errs.append(e)
ts = [threading.Thread(target=hammer) for _ in range(8)]
[t.start() for t in ts]; [t.join() for t in ts]
lib.dp_stop()
assert not errs, errs[:3]
print("TSAN_DRIVE_OK")
"""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    so = os.path.join(repo, "native", "build", "libdataplane_tsan.so")
    env = dict(os.environ, LD_PRELOAD=libtsan,
               TSAN_OPTIONS="exitcode=66", PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu")
    code = driver.format(repo=repo, so=so, work=str(work))
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    out = proc.stdout + proc.stderr
    assert "WARNING: ThreadSanitizer" not in out, out[-4000:]
    assert proc.returncode == 0 and "TSAN_DRIVE_OK" in proc.stdout, out[-4000:]
