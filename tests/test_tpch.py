"""TPC-H correctness: all 22 queries vs a sqlite oracle on SF 0.01.

Parity: the reference verifies each query against expected answers at
runtime (reference benchmarks/src/bin/tpch.rs:1017-1380, q1()..q22() tests).
Here the oracle is sqlite3 over the *same* generated data, with a dialect
translation (date literals -> int days, extract -> strftime, substring ->
substr) so one oracle covers every query.
"""
import datetime
import math
import re
import sqlite3

import numpy as np
import pandas as pd
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig
from benchmarks.datagen import generate_tables
from benchmarks.queries import QUERIES

EPOCH = datetime.date(1970, 1, 1)

# ---------------------------------------------------------------------------
# dialect translation for the sqlite oracle
# ---------------------------------------------------------------------------

_DATE_ARITH = re.compile(
    r"date\s+'(\d{4})-(\d{2})-(\d{2})'"
    r"(?:\s*([+-])\s*interval\s+'(\d+)'\s+(day|month|year))?",
    re.IGNORECASE)


def _add_interval(d: datetime.date, sign: str, n: int, unit: str) -> datetime.date:
    n = n if sign == "+" else -n
    if unit == "day":
        return d + datetime.timedelta(days=n)
    if unit == "month":
        m = d.month - 1 + n
        return d.replace(year=d.year + m // 12, month=m % 12 + 1)
    return d.replace(year=d.year + n)


def to_sqlite(sql: str) -> str:
    def date_repl(m):
        d = datetime.date(int(m.group(1)), int(m.group(2)), int(m.group(3)))
        if m.group(4):
            d = _add_interval(d, m.group(4), int(m.group(5)), m.group(6).lower())
        return str((d - EPOCH).days)

    sql = _DATE_ARITH.sub(date_repl, sql)
    sql = re.sub(
        r"extract\s*\(\s*year\s+from\s+([A-Za-z0-9_.]+)\s*\)",
        r"CAST(strftime('%Y', (\1)*86400.0, 'unixepoch') AS INTEGER)",
        sql, flags=re.IGNORECASE)
    sql = re.sub(
        r"substring\s*\(\s*([A-Za-z0-9_.]+)\s+from\s+(\d+)\s+for\s+(\d+)\s*\)",
        r"substr(\1, \2, \3)", sql, flags=re.IGNORECASE)
    return sql


def _arrow_to_oracle_df(table) -> pd.DataFrame:
    import pyarrow as pa

    cols = {}
    for name, col in zip(table.column_names, table.columns):
        t = col.type
        meta = table.schema.field(name).metadata or {}
        if pa.types.is_decimal(t):
            cols[name] = np.asarray(col.cast(pa.float64()))
        elif pa.types.is_integer(t) and meta.get(b"kind") == b"decimal":
            # int64-stored decimal (unscaled + metadata scale; the
            # benchmark converter's physical layout) -> float value domain
            scale = int(meta.get(b"scale", b"0"))
            cols[name] = np.asarray(col.cast(pa.int64())).astype(
                np.float64) / (10 ** scale)
        elif pa.types.is_date32(t):
            cols[name] = np.asarray(col.cast(pa.int32()))
        else:
            cols[name] = col.to_pandas()
    return pd.DataFrame(cols)


@pytest.fixture(scope="module")
def data():
    return generate_tables(0.01, seed=1)


@pytest.fixture(scope="module")
def oracle(data):
    conn = sqlite3.connect(":memory:")
    # SQL-standard LIKE is case-sensitive; sqlite defaults to insensitive
    conn.execute("PRAGMA case_sensitive_like = ON")
    for name, table in data.items():
        df = _arrow_to_oracle_df(table)
        df.to_sql(name, conn, index=False)
    return conn


@pytest.fixture(scope="module")
def ctx(data):
    config = BallistaConfig({"ballista.shuffle.partitions": "4"})
    c = BallistaContext.local(config)
    for name, table in data.items():
        c.register_table(name, table)
    return c


def normalize(df: pd.DataFrame) -> pd.DataFrame:
    out = {}
    for i, col in enumerate(df.columns):
        s = df[col]
        if pd.api.types.is_datetime64_any_dtype(s):
            s = (s - pd.Timestamp(EPOCH)).dt.days
        elif s.dtype == object and len(s) and isinstance(
                s.dropna().iloc[0] if len(s.dropna()) else None, datetime.date):
            s = s.map(lambda d: (d - EPOCH).days if d is not None else None)
        out[f"c{i}"] = s.reset_index(drop=True)
    return pd.DataFrame(out)


def compare_content(got: pd.DataFrame, want: pd.DataFrame):
    """Multiset equality: both frames fully sorted (ORDER BY ties are
    nondeterministic across engines, so row order is checked separately by
    ``check_ordering``)."""
    g, w = normalize(got), normalize(want)
    assert g.shape == w.shape, f"shape {g.shape} != {w.shape}\n{g}\n{w}"
    cols = list(g.columns)
    g = g.sort_values(cols, kind="mergesort").reset_index(drop=True)
    w = w.sort_values(cols, kind="mergesort").reset_index(drop=True)
    for col in cols:
        gc, wc = g[col], w[col]
        if pd.api.types.is_numeric_dtype(gc) and pd.api.types.is_numeric_dtype(wc):
            np.testing.assert_allclose(
                gc.to_numpy(dtype=np.float64), wc.to_numpy(dtype=np.float64),
                rtol=1e-6, atol=1e-6, err_msg=f"column {col}")
        else:
            assert gc.astype(str).tolist() == wc.astype(str).tolist(), \
                f"column {col}:\n{gc}\n{wc}"


def check_ordering(sql: str, got: pd.DataFrame):
    """Verify the engine honoured ORDER BY: for every order key that is an
    output column, rows must be monotone in query order (ties broken by the
    later keys; a lexicographic stability check over the key prefix)."""
    from arrow_ballista_tpu.sql import ast as qast
    from arrow_ballista_tpu.sql.parser import parse_sql

    stmt = parse_sql(sql)
    if not isinstance(stmt, qast.Select) or not stmt.order_by or len(got) < 2:
        return
    keys = []
    for item in stmt.order_by:
        e = item.expr
        if isinstance(e, qast.ColumnRef) and e.table is None and e.name in got.columns:
            keys.append((e.name, item.ascending))
        else:
            return  # expression keys: content check only
    g = normalize(got[[k for k, _ in keys]])
    g.columns = [k for k, _ in keys]

    # pairwise lexicographic comparison honoring asc/desc
    def le(r1, r2):
        for (k, asc) in keys:
            v1, v2 = r1[k], r2[k]
            if v1 == v2:
                continue
            return (v1 < v2) if asc else (v1 > v2)
        return True

    recs = g.to_dict("records")
    for i in range(len(recs) - 1):
        assert le(recs[i], recs[i + 1]), \
            f"ORDER BY violated at row {i}: {recs[i]} !<= {recs[i+1]} for {keys}"


def run_query(ctx, oracle, q: int):
    sql = QUERIES[q]
    got = ctx.sql(sql).to_pandas()
    want = pd.read_sql_query(to_sqlite(sql), oracle)
    compare_content(got.copy(), want.copy())
    check_ordering(sql, got)


@pytest.mark.parametrize("q", sorted(QUERIES))
def test_tpch_query(ctx, oracle, q):
    run_query(ctx, oracle, q)


@pytest.fixture(scope="module")
def mesh_ctx(data):
    config = BallistaConfig({"ballista.shuffle.partitions": "4",
                             "ballista.shuffle.mesh": "true",
        "ballista.shuffle.mesh.min_rows": "0"})
    c = BallistaContext.local(config)
    for name, table in data.items():
        c.register_table(name, table)
    return c


@pytest.mark.parametrize("q", sorted(QUERIES))
def test_tpch_query_mesh(mesh_ctx, oracle, q):
    """All 22 queries under the mesh config: fused operators where the
    pattern fits, clean fallback elsewhere — the safety net for running
    the mesh transport across the whole suite, not just q1/q3/q6."""
    run_query(mesh_ctx, oracle, q)
