"""PR 6 observability: runtime stats store, EXPLAIN ANALYZE, history.

Three layers, matching how the stats pipeline is built:

  1. pure math (quantiles / histogram / skew) tested directly, including
     the invariant that the stats store and the speculation policy share
     ONE nearest-rank quantile implementation;
  2. stats-store folding driven on a bare ExecutionGraph with fabricated
     completions (test_scheduler helpers), including the attempt-dedup
     regression with a late speculative loser;
  3. end-to-end EXPLAIN ANALYZE through a standalone cluster on q1- and
     q18-shaped queries, plus the REST surfaces
     (`/api/job/<id>/stats`, `/api/cluster/history`).
"""
import json
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.obs.stats import (
    ClusterHistory,
    RuntimeStatsStore,
    duration_quantiles,
    nearest_rank_quantile,
    row_histogram,
    skew_coefficient,
    stage_summary,
)
from arrow_ballista_tpu.scheduler.execution_graph import (
    SUCCESSFUL,
    ExecutionGraph,
)
from arrow_ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
from arrow_ballista_tpu.scheduler.speculation import speculation_cutoff_s
from arrow_ballista_tpu.scheduler.types import TaskStatus
from arrow_ballista_tpu.utils.config import BallistaConfig

from .test_scheduler import (
    drain,
    fake_success,
    physical_plan,
    run_job,
    scheduler_test,
)


# --------------------------------------------------------------------------
# pure math
# --------------------------------------------------------------------------

def test_nearest_rank_quantile():
    assert nearest_rank_quantile([], 0.5) is None
    assert nearest_rank_quantile([7.0], 0.95) == 7.0
    # nearest-rank over 4 samples: rank = ceil(q*4)
    xs = [4.0, 1.0, 3.0, 2.0]
    assert nearest_rank_quantile(xs, 0.5) == 2.0
    assert nearest_rank_quantile(xs, 0.75) == 3.0
    assert nearest_rank_quantile(xs, 0.95) == 4.0
    # clamped, not extrapolated
    assert nearest_rank_quantile(xs, 9.0) == 4.0
    assert nearest_rank_quantile(xs, -1.0) == 1.0


def test_quantile_shared_with_speculation_policy():
    """The speculation cutoff must be exactly quantile * multiplier — the
    policy reuses obs.stats.nearest_rank_quantile, not a private copy."""
    durations = [0.5, 1.0, 2.0, 4.0, 8.0]
    for q in (0.5, 0.75, 0.95):
        base = nearest_rank_quantile(durations, q)
        assert speculation_cutoff_s(durations, q, 2.0, 0.0) \
            == pytest.approx(base * 2.0)


def test_row_histogram_and_overflow():
    h = row_histogram([0, 5, 50, 5_000_000, 10 ** 12])
    assert sum(h["counts"]) == 5
    assert len(h["counts"]) == len(h["edges"]) + 1
    assert h["counts"][-1] == 1, "10^12 rows lands in the overflow bucket"
    assert row_histogram([])["counts"] == [0] * (len(h["edges"]) + 1)


def test_skew_coefficient():
    assert skew_coefficient([]) == 0.0
    assert skew_coefficient([0, 0]) == 0.0
    assert skew_coefficient([10, 10, 10]) == pytest.approx(1.0)
    # one hot partition: max=90 mean=30 -> 3.0
    assert skew_coefficient([90, 0, 0]) == pytest.approx(3.0)


def test_duration_quantiles_schema():
    d = duration_quantiles([0.1, 0.2, 0.3, 0.4])
    assert d["count"] == 4
    assert d["p50"] == pytest.approx(0.2)
    assert d["p95"] == pytest.approx(0.4)
    assert d["max"] == pytest.approx(0.4)
    assert d["mean"] == pytest.approx(0.25)
    assert duration_quantiles([]) == {"count": 0}


# --------------------------------------------------------------------------
# stats-store folding on the graph
# --------------------------------------------------------------------------

def test_stats_store_folds_stage_summaries():
    graph = ExecutionGraph.build("j", physical_plan(partitions=4))
    drain(graph, "exec-0")
    assert graph.status == "successful"
    snap = graph.stats.snapshot()
    assert snap["job_id"] == "j"
    assert snap["stages"], "every completed stage must be folded"
    for summary in snap["stages"]:
        assert summary["state"] == SUCCESSFUL, \
            "folding happens AFTER the stage's state transition"
        assert summary["tasks_completed"] == summary["partitions"]
        assert set(summary["task_duration_s"]) >= {"count", "p50", "p95"}
        assert sum(summary["row_histogram"]["counts"]) \
            == len(summary["partition_rows"])
    # fake_success writes 10 rows / 100 bytes per ShuffleWritePartition;
    # uniform partitions -> no skew
    s1 = snap["stages"][0]
    assert s1["skew"] == pytest.approx(1.0)
    assert s1["output_rows"] == sum(s1["partition_rows"].values())
    assert s1["output_bytes"] == sum(s1["partition_bytes"].values())
    assert snap["total_output_rows"] \
        == sum(s["output_rows"] for s in snap["stages"])


def test_stage_summary_detects_skew():
    """Per-partition reduce-side row counts come from ShuffleWritePartition
    records summed across map tasks; a hot output partition must show up
    as skew = max/mean."""
    class _W:  # ShuffleWritePartition shape
        def __init__(self, output_partition, rows, bytes_):
            self.output_partition = output_partition
            self.num_rows, self.num_bytes = rows, bytes_

    class _Info:
        state = "success"

    class _Stage:  # duck-typed: stage_summary only reads these fields
        stage_id = 1
        state = SUCCESSFUL
        stage_attempt = 0
        partitions = 2
        planned_partitions = 2
        durations = [1.0, 3.0]
        attempt_log = [{"speculative": False, "state": "success"},
                       {"speculative": True, "state": "killed"}]
        task_infos = [_Info(), _Info()]
        # two map tasks x two reduce partitions: reduce partition 0 is hot
        outputs = {0: ("exec-A", [_W(0, 900, 9000), _W(1, 20, 200)]),
                   1: ("exec-B", [_W(0, 60, 600), _W(1, 20, 200)])}

        @staticmethod
        def operator_metrics():
            return {}

    s = stage_summary(_Stage())
    assert s["partition_rows"] == {"0": 960, "1": 40}
    assert s["partition_bytes"] == {"0": 9600, "1": 400}
    assert s["skew"] == pytest.approx(960 / 500)
    assert s["task_duration_s"]["max"] == pytest.approx(3.0)
    assert s["tasks_completed"] == 2
    assert s["task_launches"] == 2 and s["speculative_launches"] == 1


def test_stats_store_atomic_snapshot_isolation():
    store = RuntimeStatsStore("jx")
    graph = ExecutionGraph.build("jx", physical_plan(partitions=2))
    drain(graph, "exec-0")
    store.fold_stage(graph.stages[1])
    before = store.stage(1)
    # refolding swaps the dict reference: a reader holding the old
    # snapshot must never observe in-place mutation
    store.fold_stage(graph.stages[1])
    assert store.stage(1) == before
    assert store.stage(1) is not before
    assert store.stage(99) is None
    assert store.stage_ids() == [1]


# --------------------------------------------------------------------------
# attempt-aware dedup: the speculative loser must not pollute stats
# --------------------------------------------------------------------------

def test_loser_attempt_excluded_from_metrics_and_profile():
    from arrow_ballista_tpu.obs.profile import _task_profile

    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    p = t.task.partition
    spec = graph.launch_speculative(1, p, "exec-B")
    win = fake_success(t, "exec-A")
    win.metrics = {"0:ShuffleWriteExec": {"output_rows": 10}}
    win.process_id = "proc-A"
    graph.update_task_status([win])
    stage = graph.stages[1]
    assert stage.operator_metrics()["0:ShuffleWriteExec"]["output_rows"] == 10

    # race: the cancelled loser's terminal status lands on the winner's
    # slot anyway (late wire delivery).  The attempt guard must reject its
    # metrics/spans even though the object is sitting in task_infos.
    lose = fake_success(spec, "exec-B")
    lose.metrics = {"0:ShuffleWriteExec": {"output_rows": 999}}
    lose.process_id = "proc-B"
    stage.task_infos[p].status = lose
    assert "0:ShuffleWriteExec" not in stage.operator_metrics(), \
        "a status from attempt N+1 on an attempt-N slot is not this task's run"
    prof = _task_profile(stage.task_infos[p])
    assert prof["attempt"] == t.task.task_attempt
    assert "metrics" not in prof and "operators" not in prof, \
        "the loser's snapshot must not be presented as the winner's profile"

    # restore the true winner: everything reappears
    stage.task_infos[p].status = win
    assert stage.operator_metrics()["0:ShuffleWriteExec"]["output_rows"] == 10
    assert _task_profile(stage.task_infos[p])["metrics"] == win.metrics


def test_stats_fold_after_speculative_race():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    spec = graph.launch_speculative(1, t.task.partition, "exec-B")
    graph.update_task_status([fake_success(t, "exec-A")])
    # loser reports late: dropped, stats unchanged
    graph.update_task_status([fake_success(spec, "exec-B")])
    drain(graph, "exec-A")
    assert graph.status == "successful"
    s1 = graph.stats.stage(1)
    assert s1["tasks_completed"] == s1["partitions"]
    assert s1["speculative_launches"] == 1
    assert s1["task_launches"] == s1["partitions"] + 1
    assert len(s1["task_duration_s"]) > 1 \
        and s1["task_duration_s"]["count"] == s1["partitions"], \
        "only winning attempts feed the duration baseline"


# --------------------------------------------------------------------------
# event-loop instrumentation + metrics gauges
# --------------------------------------------------------------------------

def test_event_loop_stats_and_cluster_sample():
    server, _ = scheduler_test()
    try:
        status = run_job(server, physical_plan())
        assert status.state == "successful"
        ev = server._event_loop.stats()
        assert ev["events_processed"] > 0
        assert ev["queue_depth"] == 0, "drained after the job completed"
        assert ev["max_lag_s"] >= ev["last_lag_s"] >= 0.0
        assert ev["handler_seconds_max"] >= ev["handler_seconds_mean"] >= 0.0
        sample = server.cluster_sample()
        for key in ("ts", "executors_alive", "total_slots", "utilization",
                    "pending_tasks", "admission_queue_depth",
                    "event_queue_depth", "event_loop_lag_s", "slow_events"):
            assert key in sample, f"cluster sample missing {key}"
        assert 0.0 <= sample["utilization"] <= 1.0
        server.history.record(sample)
        snap = server.history.snapshot()
        assert snap["samples"][-1] == sample
    finally:
        server.shutdown()


def test_event_loop_gauges_in_prometheus_text():
    m = InMemoryMetricsCollector()
    m.set_event_queue_depth(3)
    m.set_event_loop_lag(0.25)
    text = m.gather()
    assert "# TYPE scheduler_event_queue_depth gauge" in text
    assert "scheduler_event_queue_depth 3" in text
    assert "# TYPE scheduler_event_loop_lag_seconds gauge" in text
    assert "scheduler_event_loop_lag_seconds 0.25" in text


def test_cluster_history_ring_buffer():
    h = ClusterHistory(capacity=3, interval_s=0.5)
    for i in range(5):
        h.record({"ts": i})
    snap = h.snapshot()
    assert snap["capacity"] == 3 and snap["interval_s"] == 0.5
    assert [s["ts"] for s in snap["samples"]] == [2, 3, 4], \
        "oldest samples evicted at capacity"


# --------------------------------------------------------------------------
# EXPLAIN ANALYZE end-to-end (standalone)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ctx():
    c = BallistaContext.standalone(
        BallistaConfig({"ballista.shuffle.partitions": "4"}),
        concurrent_tasks=2, num_executors=2)
    rng = np.random.default_rng(7)
    n = 2000
    c.register_table("lineitem", pa.table({
        "okey": pa.array(rng.integers(0, 200, n), type=pa.int64()),
        "flag": pa.array(rng.integers(0, 3, n), type=pa.int64()),
        "qty": pa.array(rng.integers(1, 50, n), type=pa.int64()),
        "price": pa.array(rng.random(n) * 1000, type=pa.float64()),
    }))
    c.register_table("orders", pa.table({
        "okey": pa.array(np.arange(200), type=pa.int64()),
        "cust": pa.array(np.arange(200) % 17, type=pa.int64()),
    }))
    yield c
    c.shutdown()


def _check_report(report):
    # wall_time is only known client-side; REST reports stage evidence with
    # wall_time_ms=0 (it never observed the submit-to-collect window)
    assert report["state"] == "successful"
    assert report["wall_time_ms"] >= 0
    assert isinstance(report["text"], str) and "Stage" in report["text"]
    assert report["stages"]
    saw_rows = saw_time = False
    for st in report["stages"]:
        assert "skew" in st and st["skew"] >= 0.0
        assert "partition_rows" in st and "task_duration_s" in st
        tree = st["operator_tree"]
        assert tree, "every stage annotates its physical operator tree"
        for op in tree:
            assert {"path", "depth", "op", "label"} <= set(op)
            assert "rows" in op and "time_ms" in op and "bytes" in op
            saw_rows |= op["rows"] is not None
            saw_time |= bool(op["time_ms"])
    assert saw_rows, "at least one operator reports actual output rows"
    assert saw_time, "at least one operator reports actual wall time"


def test_explain_analyze_q1_shape(ctx):
    report = ctx.explain_analyze(
        "select flag, sum(qty) as sq, sum(price) as sp, count(*) as c "
        "from lineitem where qty < 45 group by flag order by flag")
    _check_report(report)
    assert report["wall_time_ms"] > 0, "client-side report times the run"
    assert report["rows_returned"] == 3
    # the aggregate numbers in the report agree with the profile endpoint
    # by construction (same operator_metrics fold) — spot check rows
    total = sum(st["output_rows"] for st in report["stages"])
    assert total == report["total_output_rows"] > 0


def test_explain_analyze_q18_shape(ctx):
    report = ctx.explain_analyze(
        "select o.cust, sum(l.qty) as s from lineitem l "
        "join orders o on l.okey = o.okey "
        "group by o.cust order by s desc limit 5")
    _check_report(report)
    assert report["rows_returned"] == 5
    labels = " ".join(op["op"] for st in report["stages"]
                      for op in st["operator_tree"])
    assert "Join" in labels or "HashJoin" in labels


def test_explain_analyze_sql_statement(ctx):
    out = ctx.sql("EXPLAIN ANALYZE select count(*) as c from lineitem") \
        .to_pandas()
    kinds = out.plan_type.tolist()
    assert kinds == ["logical_plan", "physical_plan", "explain_analyze"]
    txt = out.plan.iloc[kinds.index("explain_analyze")]
    assert "Stage" in txt and "rows" in txt


def test_explain_analyze_consistent_with_profile(ctx):
    ctx.explain_analyze(
        "select flag, count(*) as c from lineitem group by flag")
    sched = ctx._standalone.scheduler
    job_id = ctx._standalone.last_job_id
    graph = sched.jobs.get_graph(job_id)
    prof = sched.obs.get_profile(job_id, graph=graph)
    by_stage = {st["stage_id"]: st["operators"] for st in prof["stages"]}
    for sid in graph.stats.stage_ids():
        assert graph.stats.stage(sid)["operators"] == by_stage[sid], \
            "stats store and profile must report identical operator folds"


# --------------------------------------------------------------------------
# REST round-trips
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def rest(ctx):
    from arrow_ballista_tpu.scheduler.rest import RestApi
    api = RestApi(ctx._standalone.scheduler)
    api.start()
    yield api
    api.stop()


def _get(api, path, as_json=True):
    url = f"http://127.0.0.1:{api.port}{path}"
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read().decode()
    return json.loads(body) if as_json else body


def test_rest_job_stats(ctx, rest):
    ctx.sql("select flag, sum(qty) s from lineitem group by flag").collect()
    job_id = ctx._standalone.last_job_id
    report = _get(rest, f"/api/job/{job_id}/stats")
    assert report["job_id"] == job_id
    _check_report(report)


def test_rest_job_stats_unknown_job(rest):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(rest, "/api/job/zzz-nope/stats")
    assert e.value.code == 404


def test_rest_cluster_history(rest):
    hist = _get(rest, "/api/cluster/history")
    assert hist["capacity"] >= 1 and hist["interval_s"] > 0
    assert isinstance(hist["samples"], list)
    now = hist["now"]
    assert now["total_slots"] >= now["total_slots"] - now["available_slots"] >= 0
    assert "event_loop_lag_s" in now and "event_queue_depth" in now


def test_rest_dot_includes_stage_stats(ctx, rest):
    ctx.sql("select flag, count(*) c from lineitem group by flag").collect()
    job_id = ctx._standalone.last_job_id
    dot = _get(rest, f"/api/job/{job_id}/dot", as_json=False)
    assert "rows" in dot and "skew" in dot, \
        "dot export annotates completed stages with folded stats"
