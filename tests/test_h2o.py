"""h2o/db-benchmark groupby harness smoke (benchmarks/h2o.py), vs a
pandas oracle on the shared generator output."""
import json
import subprocess
import sys

import pytest

REPO = __file__.rsplit("/tests/", 1)[0]


def test_h2o_generate_and_benchmark(tmp_path):
    sys.path.insert(0, REPO)
    from __graft_entry__ import _scrubbed_cpu_env

    env = _scrubbed_cpu_env(1)
    d = str(tmp_path / "g1")
    r = subprocess.run(
        [sys.executable, "-m", "benchmarks.h2o", "generate",
         "--rows", "20000", "--groups", "10", "--out", d],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    r = subprocess.run(
        [sys.executable, "-m", "benchmarks.h2o", "benchmark",
         "--data", d, "--iterations", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in r.stdout.splitlines()
             if l.startswith("{")]
    summary = lines[-1]
    assert summary["queries_failed"] == 0
    assert summary["queries_ok"] == 7
    per = {l["query"]: l for l in lines if "query" in l}
    assert per["q1"]["rows"] == 10  # 10 id1 groups
    # oracle: q5 sums by id6
    import pandas as pd
    import pyarrow.parquet as pq

    df = pq.read_table(d + "/x.parquet").to_pandas()
    assert per["q5"]["rows"] == df.id6.nunique()
    assert per["q10"]["rows"] == len(
        df.groupby(["id1", "id2", "id3", "id4", "id5", "id6"]))
