"""Collision-stress mode: every hash64 collides (constant), results must
not change.

Parity: the reference's ``force_hash_collisions`` feature
(reference ballista/core/Cargo.toml:40-41) exists to prove join/agg/shuffle
correctness never depends on hash quality.  Here the engine re-verifies
real key equality after every hash probe and shuffles by bucket id only,
so a constant hash merely stresses skew (one bucket) and join fan-out
(every probe matches the whole build range).

The flag is process-level (jit programs bake it in at trace time, like the
reference's compile-time feature), so each configuration runs in a fresh
subprocess and the outputs are compared.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DRIVER = r"""
import json, os, sys
sys.path.insert(0, os.environ["BALLISTA_REPO"])
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig
from arrow_ballista_tpu.ops import kernels as K

out_dir = sys.argv[1]
rng = np.random.default_rng(3)
n_fact, n_dim = 3000, 200
pq.write_table(pa.table({
    "k": rng.integers(0, n_dim, n_fact).astype(np.int64),
    "s": np.array(["g%d" % v for v in rng.integers(0, 7, n_fact)]),
    "v": rng.integers(0, 1000, n_fact).astype(np.int64),
}), out_dir + "/fact.parquet")
pq.write_table(pa.table({
    "k": np.arange(n_dim, dtype=np.int64),
    "name": np.array(["d%03d" % i for i in range(n_dim)]),
}), out_dir + "/dim.parquet")

results = {"collisions": K.force_hash_collisions()}
for mesh in (False, True):
    cfg = {"ballista.shuffle.partitions": "4"}
    if mesh:
        cfg["ballista.shuffle.mesh"] = "true"
        cfg["ballista.shuffle.mesh.min_rows"] = "0"
    ctx = BallistaContext.standalone(BallistaConfig(cfg), concurrent_tasks=2)
    ctx.register_parquet("fact", out_dir + "/fact.parquet")
    ctx.register_parquet("dim", out_dir + "/dim.parquet")
    tag = "mesh" if mesh else "file"
    results["join_" + tag] = ctx.sql(
        "select d.name, count(*) as n, sum(f.v) as sv from fact f "
        "join dim d on f.k = d.k group by d.name order by sv desc, d.name "
        "limit 20").to_pandas().to_csv(index=False)
    results["agg_" + tag] = ctx.sql(
        "select s, count(*) as n, sum(v) as sv, min(v) as mn, max(v) as mx "
        "from fact group by s order by s").to_pandas().to_csv(index=False)
    results["semi_" + tag] = ctx.sql(
        "select count(*) as n from fact where k in "
        "(select k from dim where k < 50)").to_pandas().to_csv(index=False)
    ctx.shutdown()
print("RESULT:" + json.dumps(results))
"""


def _run(tmp_path, forced: bool) -> dict:
    sys.path.insert(0, REPO)
    from __graft_entry__ import _scrubbed_cpu_env

    env = _scrubbed_cpu_env(8)
    env["BALLISTA_FORCE_HASH_COLLISIONS"] = "1" if forced else "0"
    env["BALLISTA_REPO"] = REPO
    d = tmp_path / ("forced" if forced else "plain")
    d.mkdir()
    driver = tmp_path / "driver.py"
    driver.write_text(DRIVER)
    r = subprocess.run([sys.executable, str(driver), str(d)],
                       capture_output=True, text=True, cwd=REPO,
                       env=env, timeout=900)
    assert r.returncode == 0, r.stderr[-4000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("RESULT:")][-1]
    return json.loads(line[len("RESULT:"):])


def test_forced_collisions_change_nothing(tmp_path):
    plain = _run(tmp_path, forced=False)
    forced = _run(tmp_path, forced=True)
    assert plain["collisions"] is False
    assert forced["collisions"] is True
    for key in plain:
        if key == "collisions":
            continue
        assert plain[key] == forced[key], f"{key} diverged under collisions"
