"""Executor Arrow Flight data plane: a STOCK pyarrow.flight client fetches
shuffle partitions straight off an executor (reference
ballista/executor/src/flight_service.rs:82-120 — do_get(FetchPartition)).
"""
import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    sched = SchedulerNetService(
        "127.0.0.1", 0,
        config=BallistaConfig({"ballista.shuffle.partitions": "2"}))
    sched.start()
    work = str(tmp_path_factory.mktemp("exec-flight"))
    ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                        work_dir=work, concurrent_tasks=2,
                        executor_id="flight-dp-exec", flight_port=0)
    ex.start()
    yield sched, ex
    ex.stop(notify=False)
    sched.stop()


def _one_shuffle_file(sched) -> str:
    jobs = list(sched.server.jobs._status)
    graph = sched.server.jobs.get_graph(jobs[-1])
    for sid in sorted(graph.stages):
        for locs in graph.stages[sid].output_locations().values():
            for loc in locs:
                if loc.num_rows and os.path.exists(loc.path):
                    return loc.path
    raise AssertionError("no shuffle file found")


def test_stock_flight_client_fetches_partition(cluster):
    sched, ex = cluster
    ctx = BallistaContext.remote("127.0.0.1", sched.port,
                                 BallistaConfig({"ballista.shuffle.partitions": "2"}))
    rng = np.random.default_rng(9)
    ctx.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 5, 5000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 5000).astype(np.int64)),
    }))
    out = ctx.sql("select g, sum(v) as s from t group by g order by g").to_pandas()
    assert len(out) == 5

    path = _one_shuffle_file(sched)
    client = fl.connect(f"grpc://127.0.0.1:{ex.flight.port}")
    # raw-path ticket
    table = client.do_get(fl.Ticket(path.encode())).read_all()
    assert table.num_rows > 0
    # JSON ticket
    table2 = client.do_get(fl.Ticket(
        json.dumps({"path": path}).encode())).read_all()
    assert table2.num_rows == table.num_rows


def test_traversal_guard(cluster):
    _, ex = cluster
    client = fl.connect(f"grpc://127.0.0.1:{ex.flight.port}")
    with pytest.raises(fl.FlightServerError):
        client.do_get(fl.Ticket(b"/etc/passwd")).read_all()


def test_token_auth(tmp_path):
    from arrow_ballista_tpu.executor.flight_service import ExecutorFlightServer
    from arrow_ballista_tpu.models.ipc import write_ipc_file
    from arrow_ballista_tpu.models.batch import ColumnBatch
    from arrow_ballista_tpu.models.schema import Field, INT64, Schema

    sch = Schema([Field("x", INT64)])
    b = ColumnBatch.from_numpy(sch, {"x": np.arange(10, dtype=np.int64)})
    path = str(tmp_path / "part.arrow")
    write_ipc_file(b, path)
    srv = ExecutorFlightServer(str(tmp_path), token="sekrit")
    srv.start()
    try:
        client = fl.connect(f"grpc://127.0.0.1:{srv.port}")
        with pytest.raises(fl.FlightError):
            client.do_get(fl.Ticket(path.encode())).read_all()
        t = client.do_get(fl.Ticket(json.dumps(
            {"path": path, "token": "sekrit"}).encode())).read_all()
        assert t.num_rows == 10
    finally:
        srv.stop()
