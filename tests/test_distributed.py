"""Standalone-cluster integration tests: full stage DAG over shuffle files.

Parity: reference client context tests run real scheduler+executor
in-process (context.rs:530-978) — SQL over registered tables, multiple
executors, results checked against a pandas oracle.
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.utils.config import BallistaConfig


@pytest.fixture(scope="module")
def tables(rng=None):
    rng = np.random.default_rng(7)
    n = 20_000
    orders = pa.table({
        "o_id": pa.array(np.arange(n, dtype=np.int64)),
        "o_cust": pa.array(rng.integers(0, 500, n).astype(np.int64)),
        "o_total": pa.array(rng.integers(1, 1000, n).astype(np.int64)),
        "o_flag": pa.array(rng.integers(0, 3, n).astype(np.int64)),
    })
    cust = pa.table({
        "c_id": pa.array(np.arange(500, dtype=np.int64)),
        "c_region": pa.array(rng.integers(0, 5, 500).astype(np.int64)),
    })
    return orders, cust


@pytest.fixture(scope="module")
def ctx(tables):
    orders, cust = tables
    config = BallistaConfig({"ballista.shuffle.partitions": "4"})
    c = BallistaContext.standalone(config, concurrent_tasks=4, num_executors=2)
    c.register_table("orders", orders)
    c.register_table("cust", cust)
    yield c
    c.shutdown()


def test_distributed_aggregate(ctx, tables):
    orders, _ = tables
    got = ctx.sql("select o_flag, sum(o_total) as s, count(*) as n "
                  "from orders group by o_flag order by o_flag").to_pandas()
    want = (orders.to_pandas().groupby("o_flag")
            .agg(s=("o_total", "sum"), n=("o_total", "size"))
            .reset_index().sort_values("o_flag").reset_index(drop=True))
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_distributed_join(ctx, tables):
    orders, cust = tables
    got = ctx.sql(
        "select c_region, sum(o_total) as s from orders "
        "join cust on o_cust = c_id group by c_region order by c_region"
    ).to_pandas()
    pdf = orders.to_pandas().merge(cust.to_pandas(), left_on="o_cust",
                                   right_on="c_id")
    want = (pdf.groupby("c_region").agg(s=("o_total", "sum"))
            .reset_index().sort_values("c_region").reset_index(drop=True))
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_distributed_filter_topk(ctx, tables):
    orders, _ = tables
    got = ctx.sql("select o_id, o_total from orders where o_total > 900 "
                  "order by o_total desc, o_id limit 10").to_pandas()
    pdf = orders.to_pandas()
    want = (pdf[pdf.o_total > 900]
            .sort_values(["o_total", "o_id"], ascending=[False, True])
            .head(10)[["o_id", "o_total"]].reset_index(drop=True))
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_consecutive_jobs_share_cluster(ctx):
    for _ in range(3):
        out = ctx.sql("select count(*) as n from orders").to_pandas()
        assert int(out["n"][0]) == 20_000


def test_execution_error_surfaces(ctx):
    from arrow_ballista_tpu.utils.errors import BallistaError

    with pytest.raises(BallistaError):
        # parser/planner failure surfaces as an error, not a hang
        ctx.sql("select no_such_col from orders").to_pandas()
