"""bench.py parent-side merge logic: the rules that shape the driver's
BENCH artifact (TPU headline whenever the TPU worker measured an engine
query; CPU otherwise, with TPU partial evidence attached)."""
import importlib.util
import sys

REPO = __file__.rsplit("/tests/", 1)[0]
spec = importlib.util.spec_from_file_location("bench_mod", REPO + "/bench.py")
bench = importlib.util.module_from_spec(spec)
sys.modules["bench_mod"] = bench
spec.loader.exec_module(bench)


CPU = {"metric": "tpch_q1_sf1_engine_rows_per_sec", "value": 100.0,
       "unit": "rows/s", "vs_baseline": 0.5, "platform": "cpu",
       "engine": {"q1_ms": 400.0}}


def test_tpu_engine_wins_headline():
    tpu = {"metric": "tpch_q1_sf1_engine_rows_per_sec", "value": 50.0,
           "unit": "rows/s", "vs_baseline": 0.25, "platform": "tpu",
           "engine": {"q1_ms": 800.0}}
    out = bench._merge(CPU, tpu)
    assert out["platform"] == "tpu"
    assert out["value"] == 50.0
    assert out["cpu"]["value"] == 100.0  # CPU evidence rides along


def test_partial_tpu_attaches_to_cpu_headline():
    tpu = {"metric": "tpch_q1_sf1_engine_rows_per_sec", "value": 0.0,
           "unit": "rows/s", "vs_baseline": 0.0, "platform": "tpu",
           "partial": "kernel-q1", "kernel_q1_ms": 12.0}
    out = bench._merge(CPU, tpu)
    assert out["platform"] == "cpu" and out["value"] == 100.0
    assert out["tpu_partial"]["kernel_q1_ms"] == 12.0


def test_each_side_alone_and_neither():
    assert bench._merge(CPU, None)["platform"] == "cpu"
    tpu = {"metric": "m", "value": 1.0, "unit": "rows/s", "vs_baseline": 0,
           "platform": "tpu", "engine": {"q1_ms": 5.0}}
    assert bench._merge(None, tpu)["platform"] == "tpu"
    out = bench._merge(None, None)
    assert out["value"] == 0.0 and "error" in out
