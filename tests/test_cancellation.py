"""Between-batch task cancellation: a cancelled job's in-flight tasks stop
at the next operator/partition boundary and free their slot, instead of
running the whole plan to completion (reference abortable execution,
executor.rs:114-144)."""
import threading
import time

import numpy as np
import pyarrow as pa

from arrow_ballista_tpu.executor.executor import Executor
from arrow_ballista_tpu.models.schema import Field, INT64, Schema
from arrow_ballista_tpu.ops.operators import SortExec
from arrow_ballista_tpu.ops.physical import MemoryScanExec, TaskContext
from arrow_ballista_tpu.ops.shuffle import ShuffleWriterExec
from arrow_ballista_tpu.models import expr as E
from arrow_ballista_tpu.scheduler.types import (
    ExecutorMetadata,
    TaskDescription,
    TaskId,
)


class SlowScan(MemoryScanExec):
    """A scan whose partitions take ~0.15 s each: long enough that a
    50-partition plan runs ~7 s uncancelled, fast enough that the
    at-boundary cancel check proves itself in well under a second."""

    def _read_partition(self, partition: int):
        time.sleep(0.15)
        return super()._read_partition(partition)


def test_cancel_frees_slot_between_partitions(tmp_path):
    schema = Schema([Field("v", INT64)])
    table = pa.table({"v": pa.array(np.arange(5000, dtype=np.int64))})
    scan = SlowScan(schema, table, partitions=50)
    # SortExec pulls every input partition in a loop with a cancel check
    # per iteration — the common shape of a long-running final stage
    plan = ShuffleWriterExec(SortExec(scan, [(E.Column("v"), True)]),
                             partitioning=None, stage_id=1)

    ex = Executor(ExecutorMetadata(executor_id="cancel-ex", task_slots=1),
                  str(tmp_path), concurrent_tasks=1)
    task = TaskDescription(TaskId("jobc", 1, 0), plan)

    result = {}

    def run():
        result["status"] = ex.run_task(task)

    t = threading.Thread(target=run)
    t0 = time.monotonic()
    t.start()
    time.sleep(0.4)  # a couple of partitions in
    ex.cancel_job_tasks("jobc")
    t.join(timeout=10)
    elapsed = time.monotonic() - t0
    assert not t.is_alive(), "task did not stop after cancellation"
    assert result["status"].state == "killed"
    # uncancelled the plan takes ~7 s; the boundary check must stop it
    # within ~one partition of the cancel
    assert elapsed < 3.0, f"cancel took {elapsed:.1f}s to take effect"
    assert ex.active_tasks() == 0


def test_check_cancelled_noop_without_probe():
    ctx = TaskContext()
    ctx.check_cancelled()  # no probe wired: must be a no-op
