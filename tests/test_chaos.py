"""Chaos recovery suite: deterministic fault injection end to end.

Every scenario drives REAL recovery machinery — no mocked failures.  A
seeded :class:`faults.FaultPlan` maps named failpoint sites (compiled into
the executor/scheduler/net code paths) to raise/delay/drop/corrupt/kill
actions; the plan's event log makes the injection schedule itself an
assertable artifact, so the same seed + same plan must reproduce the same
faults (the reproducibility test below).

The ISSUE scenarios:

1. executor killed mid-stage -> job completes, results identical,
2. shuffle fetch failure -> lineage rollback re-runs the producer,
3. status reports dropped -> reporter loop redeems them,
4. scheduler restarts mid-job -> recovers the job from persistence,
5. straggling task -> speculative duplicate wins, loser cancelled,
   results bit-identical (and the disabled-knob parity run),
6. corrupt shuffle payload -> checksum verify -> re-fetch -> producer
   re-run, never silently-wrong results.

Plus: executor quarantine after consecutive failures (observable via
metrics + REST), RPC deadline/backoff hardening, and unit coverage of the
failpoint framework itself.  Select with ``-m chaos``.
"""
import json
import logging
import socket
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu import faults
from arrow_ballista_tpu.net.retry import GiveUpError, RetryPolicy, call_with_retry
from arrow_ballista_tpu.utils.config import BallistaConfig
from arrow_ballista_tpu.utils.errors import IOError_

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    """A leaked plan would silently poison every later test in the run."""
    faults.clear()
    yield
    faults.clear()


# --------------------------------------------------------------------------
# failpoint framework units
# --------------------------------------------------------------------------

def test_disabled_failpoints_are_noops():
    assert faults.active() is None
    assert faults.inject("rpc.client.send", method="ping") is None
    assert faults.dropped("executor.status.report", executor_id="e") is False


def test_unknown_site_action_and_field_rejected():
    with pytest.raises(ValueError, match="unknown failpoint site"):
        faults.FaultRule("no.such.site", "raise")
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.FaultRule("rpc.client.send", "explode")
    with pytest.raises(ValueError, match="unknown fault rule field"):
        faults.FaultRule.from_obj({"site": "rpc.client.send",
                                   "action": "raise", "bogus": 1})
    with pytest.raises(ValueError, match="unknown fault error kind"):
        with faults.use_plan(faults.FaultPlan([faults.FaultRule(
                "rpc.client.send", "raise", error="bogus")])):
            faults.inject("rpc.client.send")


def test_on_hit_and_times_budget():
    rule = faults.FaultRule("rpc.client.send", "raise", error="io",
                            message="boom", on_hit=2, times=1)
    with faults.use_plan(faults.FaultPlan([rule])) as plan:
        assert faults.inject("rpc.client.send") is None   # hit 1: before on_hit
        with pytest.raises(IOError_, match="boom"):
            faults.inject("rpc.client.send")              # hit 2: fires
        assert faults.inject("rpc.client.send") is None   # hit 3: budget spent
    assert rule.hits == 3 and rule.fired == 1
    assert plan.schedule() == (("rpc.client.send", 0, 2, "raise"),)


def test_match_filters_string_compare():
    rule = faults.FaultRule("executor.task.before_run", "delay",
                            delay_ms=0, times=-1, match={"stage_id": 2})
    with faults.use_plan(faults.FaultPlan([rule])):
        assert faults.inject("executor.task.before_run", stage_id=1) is None
        # int ctx vs int match, and str ctx vs int match both fire
        assert faults.inject("executor.task.before_run", stage_id=2) is rule
        assert faults.inject("executor.task.before_run", stage_id="2") is rule
    assert rule.fired == 2


def test_seeded_plan_reproducible():
    spec = {"seed": 42, "rules": [{"site": "rpc.client.send",
                                   "action": "delay", "delay_ms": 0,
                                   "times": -1, "p": 0.5}]}

    def drive(plan):
        with faults.use_plan(plan):
            for _ in range(40):
                faults.inject("rpc.client.send", method="hb")
        return plan.schedule()

    a = drive(faults.FaultPlan.from_json(json.dumps(spec)))
    b = drive(faults.FaultPlan.from_json(json.dumps(spec)))
    assert a == b and 0 < len(a) < 40, "same seed => identical schedule"
    spec["seed"] = 43
    c = drive(faults.FaultPlan.from_json(json.dumps(spec)))
    assert c != a, "different seed => different schedule"


def test_corrupt_bytes_deterministic():
    data = bytes(range(256)) * 2
    out = faults.corrupt_bytes(data)
    assert len(out) == len(data) and out != data
    assert out[0] == data[0] ^ 0xFF, "byte 0 flips so magic headers break"
    assert out[97] == data[97] ^ 0xFF
    assert out[1:97] == data[1:97]
    assert faults.corrupt_bytes(data) == out


def test_configure_from_env_config_and_file(tmp_path, monkeypatch):
    spec = json.dumps({"seed": 9, "rules": [
        {"site": "scheduler.status.receive", "action": "drop", "times": 2}]})
    # env var
    monkeypatch.setenv(faults.ENV_PLAN, spec)
    plan = faults.configure()
    assert plan is faults.active() and len(plan.rules) == 1
    assert plan.seed == 9
    assert faults.configure() is plan, "configure is idempotent"
    faults.clear()
    # config key wins over (absent) env
    monkeypatch.delenv(faults.ENV_PLAN)
    plan2 = faults.configure(BallistaConfig({"ballista.faults.plan": spec}))
    assert plan2 is not None and plan2.rules[0].action == "drop"
    faults.clear()
    # @file indirection
    p = tmp_path / "plan.json"
    p.write_text(spec)
    monkeypatch.setenv(faults.ENV_PLAN, f"@{p}")
    plan3 = faults.configure()
    assert plan3 is not None and plan3.seed == 9
    # nothing set -> no plan
    monkeypatch.delenv(faults.ENV_PLAN)
    faults.clear()
    assert faults.configure(BallistaConfig()) is None


# --------------------------------------------------------------------------
# RPC hardening units
# --------------------------------------------------------------------------

def test_backoff_exponential_capped():
    p = RetryPolicy(base_backoff_s=0.1, max_backoff_s=0.5, jitter=0.0)
    assert p.backoff_s(0) == pytest.approx(0.1)
    assert p.backoff_s(1) == pytest.approx(0.2)
    assert p.backoff_s(10) == pytest.approx(0.5), "capped"
    jittered = RetryPolicy(base_backoff_s=0.1, max_backoff_s=0.5, jitter=0.5)
    for attempt in range(5):
        b = jittered.backoff_s(attempt)
        full = min(0.5, 0.1 * 2 ** attempt)
        assert full / 2 <= b <= full


def _dead_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_call_with_retry_hits_give_up_deadline():
    policy = RetryPolicy(connect_timeout_s=0.2, base_backoff_s=0.02,
                         max_backoff_s=0.05, give_up_after_s=0.3)
    t0 = time.monotonic()
    with pytest.raises(GiveUpError) as ei:
        call_with_retry("127.0.0.1", _dead_port(), "ping", policy=policy)
    assert time.monotonic() - t0 < 5.0, "give-up deadline must bound the wait"
    assert isinstance(ei.value, ConnectionError), "callers treat it as transport"
    assert isinstance(ei.value.last, OSError)


def test_remote_error_not_retried():
    from arrow_ballista_tpu.net import wire
    from arrow_ballista_tpu.net.rpc import RpcServer

    calls = []

    def handler(payload, _bin):
        calls.append(1)
        raise ValueError("handler exploded")

    server = RpcServer("127.0.0.1", 0)
    server.register("boom", handler)
    server.start()
    try:
        with pytest.raises(wire.RemoteError):
            call_with_retry("127.0.0.1", server.port, "boom",
                            policy=RetryPolicy(give_up_after_s=5.0))
        assert len(calls) == 1, \
            "the server answered: retrying would re-run a non-idempotent handler"
    finally:
        server.stop()


def test_rpc_client_send_drop_failpoint():
    from arrow_ballista_tpu.net import wire

    rule = faults.FaultRule("rpc.client.send", "drop", times=1)
    with faults.use_plan(faults.FaultPlan([rule])):
        with pytest.raises(ConnectionError, match="failpoint"):
            wire.call("127.0.0.1", 1, "ping")  # dropped before connecting
    assert rule.fired == 1


def test_throttled_logger_suppresses_and_counts(caplog):
    from arrow_ballista_tpu.utils.logsetup import ThrottledLogger

    now = [0.0]
    tl = ThrottledLogger(logging.getLogger("chaos.throttle"), interval_s=60.0,
                         clock=lambda: now[0])
    with caplog.at_level(logging.WARNING, logger="chaos.throttle"):
        assert tl.warning("hb", "heartbeat failed")
        for _ in range(5):
            assert not tl.warning("hb", "heartbeat failed")
        assert tl.warning("poll", "poll failed"), "independent interval-class"
        now[0] = 61.0
        assert tl.warning("hb", "heartbeat failed")
    assert "5 similar suppressed" in caplog.text


# --------------------------------------------------------------------------
# quarantine + liveness-window units
# --------------------------------------------------------------------------

def test_quarantine_threshold_probation_and_strike():
    from arrow_ballista_tpu.scheduler.quarantine import ExecutorQuarantine

    now = [0.0]
    q = ExecutorQuarantine(threshold=2, probation_s=10.0, clock=lambda: now[0])
    assert not q.record_failure("e1")
    assert q.record_failure("e1"), "second consecutive failure quarantines"
    assert q.is_quarantined("e1") and q.count() == 1
    snap = q.snapshot()
    assert snap["quarantined"]["e1"] == pytest.approx(10.0)
    assert snap["total_quarantined"] == 1
    # probation window elapses -> schedulable again, on probation
    now[0] = 10.0
    assert not q.is_quarantined("e1")
    assert q.snapshot()["probation"] == ["e1"]
    # one probation strike re-quarantines immediately
    assert q.record_failure("e1")
    assert q.is_quarantined("e1")
    # a success clears everything
    now[0] = 20.0
    assert not q.is_quarantined("e1")  # probation again
    q.record_success("e1")
    assert not q.record_failure("e1"), "history cleared: back to counting"
    # threshold <= 0 disables
    off = ExecutorQuarantine(threshold=0)
    assert not off.record_failure("x") and not off.is_quarantined("x")


def test_alive_window_has_no_unschedulable_gap():
    from arrow_ballista_tpu.scheduler.cluster import (
        ClusterState,
        alive_cutoff_s,
    )
    from arrow_ballista_tpu.scheduler.types import ExecutorMetadata

    assert alive_cutoff_s(180.0) == pytest.approx(120.0)
    assert alive_cutoff_s(3.0) == pytest.approx(1.5), "grace capped at half"

    cs = ClusterState()
    cs.register_executor(ExecutorMetadata("e1", task_slots=2))
    hb = cs._heartbeats["e1"]
    # inside the alive window
    hb.timestamp = time.time() - 100.0
    assert cs.alive_executors(180.0) == ["e1"]
    assert cs.expired_executors(180.0) == []
    # draining: no offers, but not yet expired — and by construction every
    # age > cutoff eventually crosses the expiry line (single timeout key)
    hb.timestamp = time.time() - 130.0
    assert cs.alive_executors(180.0) == []
    assert cs.expired_executors(180.0) == []
    # past the full timeout the reaper declares it lost
    hb.timestamp = time.time() - 200.0
    assert cs.expired_executors(180.0) == ["e1"]


def test_executor_marks_scheduler_down_and_reregisters():
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.types import ExecutorMetadata
    from arrow_ballista_tpu.utils.logsetup import ThrottledLogger

    class FakeClient:
        def __init__(self):
            self.registered = []
            self.fail = False

        def register_executor(self, meta):
            if self.fail:
                raise ConnectionError("still down")
            self.registered.append(meta.executor_id)

    es = ExecutorServer.__new__(ExecutorServer)  # state machine only, no sockets
    es._sched_state_lock = threading.Lock()
    es._scheduler_down = False
    es.retry_policy = RetryPolicy()
    es._log_throttle = ThrottledLogger(logging.getLogger("chaos.exec"),
                                       interval_s=60.0)
    es.metadata = ExecutorMetadata("unit-exec", task_slots=1)
    es.scheduler = FakeClient()

    es._mark_scheduler_up()
    assert es.scheduler.registered == [], "no outage, no re-register"
    es._mark_scheduler_down("heartbeat")
    es._mark_scheduler_down("status report")  # idempotent transition
    assert es._scheduler_down
    es._mark_scheduler_up()
    assert es.scheduler.registered == ["unit-exec"], \
        "first success after outage re-registers (scheduler may have restarted)"
    assert not es._scheduler_down
    # re-register failing flips back to down so the next success retries it
    es._mark_scheduler_down("heartbeat")
    es.scheduler.fail = True
    es._mark_scheduler_up()
    assert es._scheduler_down
    es.scheduler.fail = False
    es._mark_scheduler_up()
    assert es.scheduler.registered == ["unit-exec", "unit-exec"]


# --------------------------------------------------------------------------
# e2e helpers: real network cluster (scheduler RPC + executors + client)
# --------------------------------------------------------------------------

CHAOS_CONF = {
    "ballista.shuffle.partitions": "4",
    # fast-failure RPC policy so every scenario stays seconds-long
    "ballista.rpc.connect.timeout.seconds": "1.0",
    "ballista.rpc.read.timeout.seconds": "10.0",
    "ballista.rpc.retry.base.seconds": "0.05",
    "ballista.rpc.retry.cap.seconds": "0.2",
    "ballista.rpc.retry.deadline.seconds": "1.5",
    # both chaos executors run on 127.0.0.1, so the co-located mmap fast
    # path would bypass the remote fetch (and its failpoints) entirely —
    # these scenarios exist to exercise the network path, so disable it
    "ballista.shuffle.local.host_match": "false",
    # small streaming chunks so multi-chunk streams (and the mid-stream
    # chunk failpoints) exist even at chaos-suite data sizes
    "ballista.shuffle.wire.chunk_rows": "1024",
}

SQL = "select g, sum(v) as s, count(*) as n from t group by g order by g"


def _make_cluster(tmp_path, n_executors=2, concurrent_tasks=4, conf=None,
                  **sched_kw):
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService
    from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig

    conf_d = dict(CHAOS_CONF)
    conf_d.update(conf or {})
    sched = SchedulerNetService(
        "127.0.0.1", 0, config=BallistaConfig(conf_d),
        scheduler_config=SchedulerConfig(task_distribution="round-robin",
                                         executor_timeout_s=3.0,
                                         reaper_interval_s=0.3,
                                         **sched_kw))
    sched.start()
    executors = []
    for i in range(n_executors):
        work = tmp_path / f"exec{i}"
        work.mkdir()
        ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                            work_dir=str(work),
                            concurrent_tasks=concurrent_tasks,
                            executor_id=f"chaos-exec-{i}",
                            config=BallistaConfig(conf_d),
                            heartbeat_interval_s=0.4)
        ex.start()
        executors.append(ex)
    return sched, executors


def _teardown(sched, executors):
    for ex in executors:
        ex.stop(notify=False)
    sched.stop()


def _client(port, n=4000, groups=7, seed=11):
    from arrow_ballista_tpu.client.context import BallistaContext

    c = BallistaContext.remote(
        "127.0.0.1", port, BallistaConfig({"ballista.shuffle.partitions": "4"}))
    rng = np.random.default_rng(seed)
    c.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, groups, n).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, n).astype(np.int64)),
    }))
    return c


def _frames_equal(got, expected):
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  expected.reset_index(drop=True),
                                  check_dtype=False)


# --------------------------------------------------------------------------
# scenario 1: executor killed mid-stage -> job completes, result unchanged
# --------------------------------------------------------------------------

def test_executor_killed_mid_stage_job_completes(tmp_path):
    sched, executors = _make_cluster(tmp_path)
    try:
        c = _client(sched.port)
        baseline = c.sql(SQL).to_pandas()

        victim = executors[1]
        plan = faults.FaultPlan.from_obj({"seed": 7, "rules": [{
            "site": "executor.task.before_run", "action": "kill",
            "match": {"executor_id": victim.metadata.executor_id},
            "on_hit": 1, "times": 1}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert plan.schedule() == (("executor.task.before_run", 0, 1, "kill"),)
        assert victim._killed, "the kill action must reach the registered target"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario 2: fetch failure -> lineage rollback re-runs the producer
# --------------------------------------------------------------------------

def test_fetch_failure_rolls_back_and_reruns_producer(tmp_path):
    # concurrent_tasks=1 serializes each executor's reduce tasks, so the
    # first remote fetch of (stage 1, map partition 0) burns ALL the rule's
    # fire budget across its in-call retry attempts: a deterministic
    # FetchFailedError -> consumer rollback -> producer re-run, after which
    # the spent budget lets the re-fetch succeed.  High group cardinality
    # keeps stage-2 inputs above the adaptive-coalescing floor so reducers
    # land on both executors and a remote fetch is guaranteed.
    from arrow_ballista_tpu.net.dataplane import FETCH_RETRIES

    sched, executors = _make_cluster(tmp_path, concurrent_tasks=1)
    try:
        c = _client(sched.port, n=20_000, groups=50_000, seed=13)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 3, "rules": [{
            "site": "shuffle.fetch.recv", "action": "raise",
            "error": "connection", "message": "injected dead peer",
            "times": FETCH_RETRIES,
            "match": {"stage_id": 1, "map_partition": 0}}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert plan.schedule() == tuple(
            ("shuffle.fetch.recv", 0, k, "raise")
            for k in range(1, FETCH_RETRIES + 1)), \
            "one logical fetch must absorb the whole budget"
        # the consumer rolled back (charged) and the producer re-ran
        graphs = list(sched.server.jobs._graphs.values())
        assert any(s.failures >= 1 for g in graphs
                   for s in g.stages.values()), "no consumer rollback recorded"
        assert any(s.stage_attempt >= 1 for g in graphs
                   for s in g.stages.values()), "no producer re-run recorded"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario 3: status reports dropped -> reporter retries until delivered
# --------------------------------------------------------------------------

def test_dropped_status_reports_are_redeemed(tmp_path):
    sched, executors = _make_cluster(tmp_path)
    try:
        c = _client(sched.port)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 5, "rules": [{
            "site": "executor.status.report", "action": "drop", "times": 2}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        drops = [e for e in plan.events if e["action"] == "drop"]
        assert len(drops) == 2, "both drop budget units must be consumed"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario 4: scheduler restarts mid-job -> recovers from persistence
# --------------------------------------------------------------------------

def test_scheduler_restart_recovers_job(tmp_path):
    from arrow_ballista_tpu.executor.executor import Executor
    from arrow_ballista_tpu.models.ipc import read_ipc_files
    from arrow_ballista_tpu.scheduler.execution_graph import SUCCESSFUL
    from arrow_ballista_tpu.scheduler.persistence import FileJobStateBackend
    from arrow_ballista_tpu.scheduler.scheduler import (
        SchedulerConfig,
        SchedulerServer,
    )
    from arrow_ballista_tpu.scheduler.standalone import InProcessTaskLauncher
    from arrow_ballista_tpu.scheduler.types import ExecutorMetadata

    from .test_scheduler import physical_plan

    class KeepExecutorsLauncher(InProcessTaskLauncher):
        # SchedulerServer.shutdown() stops the launcher; the executors must
        # SURVIVE the restart (only the scheduler "crashes")
        def stop(self):
            pass

    backend = FileJobStateBackend(str(tmp_path / "state"))
    work = str(tmp_path / "work")
    launcher = KeepExecutorsLauncher()
    config = BallistaConfig({"ballista.shuffle.partitions": "4"})
    executors = []
    for i in range(2):
        meta = ExecutorMetadata(executor_id=f"chaos-inproc-{i}", task_slots=2)
        executors.append(Executor(meta, work, config, concurrent_tasks=2))
        launcher.executors[meta.executor_id] = executors[-1]

    def new_server():
        server = SchedulerServer(launcher, SchedulerConfig(),
                                 job_backend=backend,
                                 scheduler_id="chaos-sched")
        launcher.scheduler = server
        server.init(start_reaper=False)
        for ex in executors:
            server.register_executor(ex.metadata)
        return server

    # stage-2 tasks crawl so the shutdown lands mid-stage, after stage 1
    # checkpointed but before the job finishes
    plan = faults.FaultPlan.from_obj({"seed": 1, "rules": [{
        "site": "executor.task.before_run", "action": "delay",
        "delay_ms": 400, "times": -1, "match": {"stage_id": 2}}]})
    qplan = physical_plan()
    server1 = new_server()
    with faults.use_plan(plan):
        server1.submit_job("chaosjob", lambda: (qplan, {}))
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            graph = server1.jobs.get_graph("chaosjob")
            if graph is not None and graph.stages[1].state == SUCCESSFUL:
                break
            time.sleep(0.02)
        else:
            pytest.fail("stage 1 never completed")
        server1.shutdown()  # "crash": in-flight stage-2 work is abandoned

        server2 = new_server()
        assert server2.jobs.get_graph("chaosjob") is None, "fresh scheduler"
        adopted = server2.recover_jobs()
        assert adopted == ["chaosjob"], "job must be re-acquired from the backend"
        status = server2.wait_for_job("chaosjob", 60.0)
    assert status.state == "successful"
    graph2 = server2.jobs.get_graph("chaosjob")
    assert plan.events, "the delay failpoint must actually have fired"

    # results identical to the fault-free answer (same seeded data as
    # test_scheduler.physical_plan: k in [0,5), v in [0,100))
    rng = np.random.default_rng(0)
    df = pd.DataFrame({"k": rng.integers(0, 5, 1000).astype(np.int64),
                       "v": rng.integers(0, 100, 1000).astype(np.int64)})
    expected = (df.groupby("k", as_index=False).agg(s=("v", "sum"))
                .sort_values("k").reset_index(drop=True))
    paths = [loc.path for part in sorted(status.locations)
             for loc in status.locations[part] if loc.num_rows]
    batches = read_ipc_files(paths, qplan.schema, capacity=1024)
    got = pd.concat([b.to_pandas() for b in batches], ignore_index=True)
    _frames_equal(got, expected)
    assert graph2.status == "successful"
    server2.shutdown()
    for ex in executors:
        ex.shutdown()


# --------------------------------------------------------------------------
# scenario 5: repeated failures quarantine an executor (metrics + REST)
# --------------------------------------------------------------------------

def test_quarantine_bad_executor_job_still_completes():
    import urllib.request

    from arrow_ballista_tpu.scheduler.rest import RestApi
    from arrow_ballista_tpu.scheduler.scheduler import (
        SchedulerConfig,
        SchedulerServer,
    )
    from arrow_ballista_tpu.scheduler.types import (
        IO_ERROR,
        ExecutorMetadata,
        FailedReason,
        TaskStatus,
    )

    from .test_scheduler import VirtualTaskLauncher, physical_plan, run_job

    def outcome(task, executor_id):
        if executor_id == "exec-1":  # a broken host: every task fails
            return TaskStatus(task.task, executor_id, "failed",
                              failure=FailedReason(IO_ERROR, "disk on fire"))
        return None

    launcher = VirtualTaskLauncher(outcome)
    server = SchedulerServer(launcher, SchedulerConfig(
        task_distribution="round-robin",
        quarantine_failures=3, quarantine_probation_s=300.0))
    launcher.scheduler = server
    server.init(start_reaper=False)
    for i in range(2):
        server.register_executor(ExecutorMetadata(f"exec-{i}", task_slots=2))
    api = RestApi(server)
    api.start()
    try:
        status = run_job(server, physical_plan())
        # quarantine (threshold 3) must isolate exec-1 BEFORE any single
        # task burns its TASK_MAX_FAILURES=4 budget -> the job completes
        assert status.state == "successful"
        assert server.quarantine.is_quarantined("exec-1")
        assert not server.quarantine.is_quarantined("exec-0")
        # observable via prometheus metrics ...
        text = server.metrics.gather()
        assert "executor_quarantined_total 1" in text
        assert "quarantined_executors 1" in text
        # ... and over the REST API
        with urllib.request.urlopen(
                f"http://{api.host}:{api.port}/api/quarantine", timeout=10) as r:
            snap = json.loads(r.read())
        assert "exec-1" in snap["quarantined"]
        assert snap["threshold"] == 3 and snap["total_quarantined"] == 1
    finally:
        api.stop()
        server.shutdown()


# --------------------------------------------------------------------------
# scenario 6: straggler -> speculative duplicate wins, results identical
# --------------------------------------------------------------------------

def _standalone_ctx(conf_extra=None, num_executors=2):
    from arrow_ballista_tpu.client.context import BallistaContext

    conf = {"ballista.shuffle.partitions": "4"}
    conf.update(conf_extra or {})
    ctx = BallistaContext.standalone(BallistaConfig(conf),
                                     concurrent_tasks=2,
                                     num_executors=num_executors)
    rng = np.random.default_rng(23)
    ctx.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 7, 4000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 4000).astype(np.int64)),
    }))
    return ctx


def test_straggler_speculative_duplicate_wins():
    ctx = _standalone_ctx({
        "ballista.speculation.enabled": "true",
        "ballista.speculation.quantile": "0.5",
        "ballista.speculation.multiplier": "1.2",
        "ballista.speculation.min_runtime.seconds": "0.3",
        "ballista.speculation.interval.seconds": "0.1",
    })
    try:
        baseline = ctx.sql(SQL).to_pandas()

        # the first stage-1 task executor-0 runs stalls for 2 s — far past
        # the cutoff (min_runtime 0.3 s over a sub-ms baseline); the
        # monitor must duplicate it onto executor-1, whose copy wins
        plan = faults.FaultPlan.from_obj({"seed": 21, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 2000, "times": 1,
            "match": {"stage_id": 1, "executor_id": "executor-0"}}]})
        with faults.use_plan(plan):
            got = ctx.sql(SQL).to_pandas()

        assert plan.events, "the slow failpoint must actually have fired"
        _frames_equal(got, baseline)

        sched = ctx._standalone.scheduler
        text = sched.metrics.gather()
        assert "speculative_tasks_launched_total 1" in text
        assert "speculative_wins_total 1" in text
        job_id = list(sched.jobs._status)[-1]
        stage = sched.jobs.get_graph(job_id).stages[1]
        wins = [e for e in stage.attempt_log if e["state"] == "success"]
        assert any(e["speculative"] for e in wins), \
            "the duplicate attempt must be the recorded winner"
        assert len([e for e in wins if e["partition"] ==
                    next(e["partition"] for e in stage.attempt_log
                         if e["speculative"])]) == 1, \
            "first result wins exactly once per partition"
        # the cancelled straggler eventually unwinds as killed (it wakes
        # from the injected stall, sees the cancel, and reports in)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if any(e["state"] == "killed" for e in stage.attempt_log):
                break
            time.sleep(0.05)
        else:
            pytest.fail("cancelled straggler never unwound as killed: "
                        f"{stage.attempt_log}")
    finally:
        ctx.shutdown()


def test_speculation_disabled_parity():
    """The same straggler with ``ballista.speculation.enabled`` unset (the
    default): no monitor thread, no duplicate attempts, the job just waits
    out the stall and completes with identical results."""
    ctx = _standalone_ctx()
    try:
        baseline = ctx.sql(SQL).to_pandas()
        plan = faults.FaultPlan.from_obj({"seed": 21, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 700, "times": 1,
            "match": {"stage_id": 1, "executor_id": "executor-0"}}]})
        with faults.use_plan(plan):
            got = ctx.sql(SQL).to_pandas()
        assert plan.events, "the slow failpoint must actually have fired"
        _frames_equal(got, baseline)
        sched = ctx._standalone.scheduler
        assert sched._spec_monitor is None, "no monitor when disabled"
        assert "speculative_tasks_launched_total 0" in sched.metrics.gather()
        job_id = list(sched.jobs._status)[-1]
        graph = sched.jobs.get_graph(job_id)
        assert not any(e["speculative"] for s in graph.stages.values()
                       for e in s.attempt_log)
    finally:
        ctx.shutdown()


# --------------------------------------------------------------------------
# scenario 7: corrupt shuffle payload -> verify -> re-fetch -> producer
# re-run (never silently-wrong results)
# --------------------------------------------------------------------------

def test_corrupt_shuffle_payload_detected_and_recovered(tmp_path):
    # same topology as the fetch-failure scenario: concurrent_tasks=1
    # serializes the reducers so ONE logical fetch burns the whole corrupt
    # budget across its in-loop retries, and high group cardinality forces
    # a remote fetch.  Every corrupted payload must be caught by the CRC
    # BEFORE deserialization; exhausting the retries escalates to lineage
    # recovery, and the re-run producer's clean data yields exact results.
    from arrow_ballista_tpu.net.dataplane import FETCH_RETRIES

    sched, executors = _make_cluster(tmp_path, concurrent_tasks=1)
    try:
        c = _client(sched.port, n=20_000, groups=50_000, seed=19)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 6, "rules": [{
            "site": "shuffle.fetch.recv", "action": "corrupt",
            "times": FETCH_RETRIES,
            "match": {"stage_id": 1, "map_partition": 0}}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert plan.schedule() == tuple(
            ("shuffle.fetch.recv", 0, k, "corrupt")
            for k in range(1, FETCH_RETRIES + 1)), \
            "one logical fetch must absorb the whole corruption budget"
        # the checksum caught it: integrity failures counted, the consumer
        # rolled back, and the producer re-ran
        text = sched.server.metrics.gather()
        count = [int(float(line.split()[-1])) for line in text.splitlines()
                 if line.startswith("shuffle_integrity_failures_total")]
        assert count and count[0] >= 1, text
        graphs = list(sched.server.jobs._graphs.values())
        assert any(s.failures >= 1 for g in graphs
                   for s in g.stages.values()), "no consumer rollback recorded"
        assert any(s.stage_attempt >= 1 for g in graphs
                   for s in g.stages.values()), "no producer re-run recorded"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario 7: executor killed while a downstream stage is being AQE-rewritten
# -> rollback restores the planned exchange, recovery re-applies the rewrite,
# results bit-identical (ISSUE 7)
# --------------------------------------------------------------------------

def test_executor_killed_during_aqe_rewrite_recovers(tmp_path):
    # The group-by job's reduce stage (stage 2) is tiny, so the default-on
    # AQE pass coalesces it as soon as the map stage completes.  A delay
    # rule at scheduler.aqe.before_rewrite widens that rewrite window, and
    # a kill rule takes down whichever executor first RUNS a task of the
    # rewritten stage — losing half the map outputs.  Recovery must roll
    # the coalesced consumer back to its planned partitioning, re-run the
    # lost producers, re-apply the rewrite against the fresh stats, and
    # still produce bit-identical results.
    sched, executors = _make_cluster(tmp_path)
    try:
        c = _client(sched.port)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 9, "rules": [
            {"site": "scheduler.aqe.before_rewrite", "action": "delay",
             "delay_ms": 200, "times": -1},
            {"site": "executor.task.before_run", "action": "kill",
             "match": {"stage_id": 2}, "on_hit": 1, "times": 1},
        ]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        kills = [e for e in plan.events if e["action"] == "kill"]
        assert len(kills) == 1, plan.events
        assert any(ex._killed for ex in executors), \
            "the kill must reach a registered executor"
        # the rewrite fired once before the kill and again during recovery
        rewrites = [e for e in plan.events
                    if e["site"] == "scheduler.aqe.before_rewrite"]
        assert len(rewrites) >= 2, plan.events
        # the rolled-back consumer carries a rewrite record from BOTH
        # stage-attempt epochs (executor loss bumps stage_attempt)
        graphs = list(sched.server.jobs._graphs.values())
        assert any(len({r["stage_attempt"] for r in s.aqe_rewrites}) >= 2
                   and s.stage_attempt >= 1
                   for g in graphs for s in g.stages.values()), \
            "no rewritten stage was rolled back and re-rewritten"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario 8: mid-stream chunk faults on the chunked shuffle protocol —
# a single corrupted or dropped chunk heals INSIDE the fetch (resume from
# the first unverified chunk), and a persistent mid-stream loss escalates
# to lineage rollback with bit-identical results (ISSUE 8)
# --------------------------------------------------------------------------

def test_mid_stream_chunk_corruption_heals_in_fetch(tmp_path):
    # chunk_rows=1024 (CHAOS_CONF) and ~20k rows across 4x4 shuffle files
    # give every remote fetch several chunks.  Corrupting exactly ONE
    # mid-stream chunk (match {"chunk": 1}) must be caught by the per-chunk
    # CRC and healed by an immediate resume at that chunk — chunks 0..k-1
    # are already decoded and are NOT re-fetched, and the failure never
    # reaches the scheduler (no rollback, no producer re-run).
    sched, executors = _make_cluster(tmp_path, concurrent_tasks=1)
    try:
        c = _client(sched.port, n=20_000, groups=50_000, seed=29)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 8, "rules": [{
            "site": "shuffle.fetch.recv", "action": "corrupt", "times": 1,
            "match": {"stage_id": 1, "chunk": 1}}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert plan.schedule() == (("shuffle.fetch.recv", 0, 1, "corrupt"),)
        # healed in-fetch: no stage ever failed or re-ran
        graphs = list(sched.server.jobs._graphs.values())
        assert not any(s.failures for g in graphs for s in g.stages.values())
        assert not any(s.stage_attempt for g in graphs
                       for s in g.stages.values())
        _frames_equal(got, baseline)
        # the resumed retry skipped the already-verified chunk 0
        from arrow_ballista_tpu.net.dataplane import STATS
        assert STATS.snapshot()["resumed_chunks"] >= 1
        c.shutdown()
    finally:
        _teardown(sched, executors)


def test_mid_stream_chunk_drop_heals_in_fetch(tmp_path):
    # same shape with a DROPPED chunk: the stream dies mid-flight
    # (ConnectionError), the retry backs off and resumes at the lost chunk
    sched, executors = _make_cluster(tmp_path, concurrent_tasks=1)
    try:
        c = _client(sched.port, n=20_000, groups=50_000, seed=31)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 4, "rules": [{
            "site": "shuffle.fetch.recv", "action": "drop", "times": 1,
            "match": {"stage_id": 1, "chunk": 1}}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert plan.schedule() == (("shuffle.fetch.recv", 0, 1, "drop"),)
        graphs = list(sched.server.jobs._graphs.values())
        assert not any(s.failures for g in graphs for s in g.stages.values())
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


def test_mid_stream_producer_loss_rolls_back_and_recovers(tmp_path):
    # A producer that dies while serving a stream is indistinguishable from
    # a dropped connection at the consumer: every resume attempt of ONE
    # logical fetch dies at chunk 1 (times=FETCH_RETRIES burns the whole
    # in-call retry budget), so the consumer escalates FetchFailedError ->
    # lineage rollback -> producer re-run, and the re-fetch of the fresh
    # file succeeds.  Results must be bit-identical: partially-decoded
    # chunks from the dead stream are discarded with the failed task.
    from arrow_ballista_tpu.net.dataplane import FETCH_RETRIES

    sched, executors = _make_cluster(tmp_path, concurrent_tasks=1)
    try:
        c = _client(sched.port, n=20_000, groups=50_000, seed=37)
        baseline = c.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 2, "rules": [{
            "site": "shuffle.fetch.recv", "action": "drop",
            "times": FETCH_RETRIES,
            "match": {"stage_id": 1, "map_partition": 0, "chunk": 1}}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert plan.schedule() == tuple(
            ("shuffle.fetch.recv", 0, k, "drop")
            for k in range(1, FETCH_RETRIES + 1)), \
            "one logical fetch must absorb the whole drop budget"
        graphs = list(sched.server.jobs._graphs.values())
        assert any(s.failures >= 1 for g in graphs
                   for s in g.stages.values()), "no consumer rollback recorded"
        assert any(s.stage_attempt >= 1 for g in graphs
                   for s in g.stages.values()), "no producer re-run recorded"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario: memory governor denies every grant -> forced spill, results
# bit-identical to the in-memory run
# --------------------------------------------------------------------------

def test_forced_spill_results_bit_identical(tmp_path):
    from arrow_ballista_tpu.memory import STATS as mem_stats

    sched, executors = _make_cluster(tmp_path)
    try:
        c = _client(sched.port)
        baseline = c.sql(SQL).to_pandas()

        mem_stats.reset()
        plan = faults.FaultPlan.from_obj({"seed": 19, "rules": [{
            "site": "executor.memory.reserve", "action": "raise",
            "error": "resource", "times": -1}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert plan.schedule(), "the deny rule must actually have fired"
        snap = mem_stats.snapshot()
        assert snap.get("spill_runs_total", 0) > 0, \
            "denied grants must have degraded operators to the spill path"
        assert snap.get("reserved_bytes.host", 0) == 0, "no reservation leaks"
        assert sched.server.quarantine.count() == 0, \
            "governor denials must never quarantine an executor"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario: spill run corrupted on disk -> read-back CRC catches it, the
# task retry recomputes from shuffle inputs (lineage), results identical
# --------------------------------------------------------------------------

def test_spill_corruption_heals_via_lineage(tmp_path):
    from arrow_ballista_tpu.memory import STATS as mem_stats

    sched, executors = _make_cluster(tmp_path)
    try:
        c = _client(sched.port)
        baseline = c.sql(SQL).to_pandas()

        mem_stats.reset()
        plan = faults.FaultPlan.from_obj({"seed": 23, "rules": [
            # every reservation denied: all operators take the spill path,
            # including the retried task attempt
            {"site": "executor.memory.reserve", "action": "raise",
             "error": "resource", "times": -1},
            # the first spill run rots on disk after its CRC is recorded
            {"site": "executor.spill.write", "action": "corrupt",
             "times": 1},
        ]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        fired = {(site, rule) for site, rule, _hit, _a in plan.schedule()}
        assert ("executor.spill.write", 1) in fired, \
            "the corrupt rule must have fired"
        graphs = list(sched.server.jobs._graphs.values())
        assert any(f >= 1 for g in graphs for s in g.stages.values()
                   for f in s.task_failures), \
            "the CRC mismatch must have failed a task attempt (retryably)"
        assert sched.server.quarantine.count() == 0, \
            "one integrity retry must not quarantine anything"
        _frames_equal(got, baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# scenario: every executor's governor saturated -> admission sheds new
# jobs with a retriable ResourceExhausted; draining pressure re-admits
# --------------------------------------------------------------------------

def test_memory_shed_surfaces_retriable_and_recovers(tmp_path):
    from arrow_ballista_tpu.utils.config import MEM_HOST_BUDGET
    from arrow_ballista_tpu.utils.errors import ResourceExhausted

    budget = 1 << 20
    sched, executors = _make_cluster(
        tmp_path, conf={MEM_HOST_BUDGET: str(budget)},
        memory_shed_threshold=0.95)
    try:
        c = _client(sched.port)
        baseline = c.sql(SQL).to_pandas()

        # saturate every executor's governor (simulated resident state);
        # the pressure floor only rises when NO executor has headroom
        held = [ex.executor.governor.force_reserve(int(budget * 0.99))
                for ex in executors]

        def floor():
            return sched.server.cluster.min_alive_pressure(3.0)

        deadline = time.monotonic() + 10.0
        while floor() < 0.95 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert floor() >= 0.95, "heartbeats must carry the pressure in"

        with pytest.raises(ResourceExhausted) as exc:
            c.sql(SQL).to_pandas()
        assert exc.value.retryable
        assert "memory saturated" in str(exc.value)
        assert "retry after" in str(exc.value)
        assert sched.server.metrics.counters_snapshot()[
            "memory_pressure_sheds_total"] == 1
        assert sched.server.quarantine.count() == 0, \
            "shedding is back-pressure, never an executor fault"

        # drain the pressure: the next heartbeat re-opens admission
        for r in held:
            r.release()
        deadline = time.monotonic() + 10.0
        while floor() >= 0.95 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert floor() < 0.95
        _frames_equal(c.sql(SQL).to_pandas(), baseline)
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# query lifecycle guardrails: deadline expiry, lost cancel -> zombie reap,
# poison-query containment
# --------------------------------------------------------------------------

def _lifecycle_residuals(sched, executors):
    out = []
    if any(ex.active_tasks() for ex in executors):
        out.append("in-flight tasks")
    if any(ex.running_task_ids() for ex in executors):
        out.append("cancel tokens")
    if sched.cluster.total_available() != sched.cluster.total_slots():
        out.append("slot reservations")
    if sched.pending_task_count() != 0:
        out.append("pending tasks")
    if sched.jobs.active_graphs():
        out.append("active graphs")
    snap = sched.admission.snapshot()
    if snap["queued"] or snap["running"]:
        out.append("admission permits")
    return out


def _assert_lifecycle_leak_free(ctx, timeout=15.0):
    sched = ctx._standalone.scheduler
    executors = ctx._standalone.executors
    deadline = time.monotonic() + timeout
    while _lifecycle_residuals(sched, executors) \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not _lifecycle_residuals(sched, executors), \
        f"residual state: {_lifecycle_residuals(sched, executors)}"


def test_deadline_expiry_mid_stage_leaves_no_leaks():
    """Scenario: a job blows its server-side deadline with stage-2 tasks
    mid-flight.  The reaper must cancel it fleet-wide — terminal
    DeadlineExceeded, every slot/permit/token released, nothing keeps
    running."""
    from arrow_ballista_tpu.utils.errors import ExecutionError

    ctx = _standalone_ctx({"ballista.query.deadline.seconds": "2.0",
                           "ballista.journal.enabled": "true"})
    try:
        baseline = ctx.sql(SQL).to_pandas()  # well under the deadline

        plan = faults.FaultPlan.from_obj({"seed": 31, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": 6000, "times": -1, "match": {"stage_id": 2}}]})
        t0 = time.monotonic()
        with faults.use_plan(plan):
            with pytest.raises(ExecutionError, match="DeadlineExceeded"):
                ctx.sql(SQL).to_pandas()
        assert time.monotonic() - t0 < 10.0, \
            "deadline must land on the reaper cadence, not the stall"
        assert plan.events, "the stall failpoint must actually have fired"

        sched = ctx._standalone.scheduler
        job_id = ctx._standalone.last_job_id
        status = sched.jobs.get_status(job_id)
        assert status.state == "failed" and not status.retriable
        assert sched.metrics.counters_snapshot()[
            "jobs_deadline_exceeded_total"] == 1
        from arrow_ballista_tpu.obs import journal

        kinds = [e["kind"] for e in journal.job_timeline(job_id)]
        assert "job.deadline_exceeded" in kinds
        _assert_lifecycle_leak_free(ctx)
        # the session survives: the same query without the stall succeeds
        _frames_equal(ctx.sql(SQL).to_pandas(), baseline)
    finally:
        ctx._standalone.shutdown()


def test_lost_cancel_fanout_reaped_by_heartbeat():
    """Scenario: the cancel RPC fanout is dropped by the network.  The
    job goes terminal anyway; the executors keep running zombie tasks
    until their heartbeats advertise the running set and the scheduler
    re-issues the kill — within two heartbeat rounds."""
    from arrow_ballista_tpu.scheduler.types import ExecutorHeartbeat
    from arrow_ballista_tpu.utils.errors import ExecutionError

    ctx = _standalone_ctx({"ballista.journal.enabled": "true"})
    try:
        sched = ctx._standalone.scheduler
        executors = ctx._standalone.executors
        result = {}

        def run():
            try:
                ctx.sql(SQL).to_pandas()
                result["out"] = "completed"
            except ExecutionError as e:
                result["out"] = str(e)

        plan = faults.FaultPlan.from_obj({"seed": 37, "rules": [
            {"site": "executor.task.slow", "action": "delay",
             "delay_ms": 4000, "times": -1, "match": {"stage_id": 1}},
            # one lost fanout per executor, then the network heals
            {"site": "scheduler.cancel.fanout", "action": "drop",
             "times": 2},
        ]})
        with faults.use_plan(plan):
            th = threading.Thread(target=run)
            th.start()
            deadline = time.monotonic() + 10.0
            while not any(ex.active_tasks() for ex in executors) \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert any(ex.active_tasks() for ex in executors)
            job_id = ctx._standalone.last_job_id
            ctx.cancel(job_id)
            # the job is terminal for clients immediately ...
            deadline = time.monotonic() + 10.0
            while sched.jobs.get_status(job_id).state != "cancelled" \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sched.jobs.get_status(job_id).state == "cancelled"
            # ... but the dropped fanout left zombie tasks behind
            assert sum(len(ex.running_task_ids()) for ex in executors) > 0
            dropped = [e for e in plan.events
                       if e["site"] == "scheduler.cancel.fanout"]
            assert dropped, "the fanout drop must actually have fired"

            # two heartbeat rounds close the leak
            for _round in range(2):
                for ex in executors:
                    sched.heartbeat(ExecutorHeartbeat(
                        ex.metadata.executor_id,
                        running=ex.running_task_ids()))
                time.sleep(0.2)
            deadline = time.monotonic() + 10.0
            while any(ex.running_task_ids() for ex in executors) \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert not any(ex.running_task_ids() for ex in executors), \
                "zombie tasks survived two heartbeat rounds"
            th.join(timeout=15.0)
            assert not th.is_alive()

        counters = sched.metrics.counters_snapshot()
        assert counters["zombie_tasks_reaped_total"] >= 1
        from arrow_ballista_tpu.obs import journal

        kinds = [e["kind"] for e in journal.job_timeline(job_id)]
        assert "zombie.reaped" in kinds
        _assert_lifecycle_leak_free(ctx)
        assert len(ctx.sql(SQL).to_pandas()) == 7
    finally:
        ctx._standalone.shutdown()


def test_poison_query_contained_without_quarantining_fleet():
    """Scenario: a query whose split deterministically fails every
    executor it touches.  Containment must fail it fast (no retry-budget
    burn-down) with the quarantine list EMPTY — one bad query must never
    bench healthy hosts."""
    from arrow_ballista_tpu.utils.errors import ExecutionError

    ctx = _standalone_ctx({"ballista.journal.enabled": "true"})
    try:
        sched = ctx._standalone.scheduler
        baseline = ctx.sql(SQL).to_pandas()

        plan = faults.FaultPlan.from_obj({"seed": 41, "rules": [{
            "site": "executor.task.before_run", "action": "raise",
            "error": "io", "message": "poison split: unreadable block",
            "times": -1, "match": {"stage_id": 1, "partition": 0}}]})
        t0 = time.monotonic()
        with faults.use_plan(plan):
            with pytest.raises(ExecutionError, match="PoisonQuery"):
                ctx.sql(SQL).to_pandas()
        assert time.monotonic() - t0 < 10.0, "containment must be fast"

        job_id = ctx._standalone.last_job_id
        status = sched.jobs.get_status(job_id)
        assert status.state == "failed" and not status.retriable
        counters = sched.metrics.counters_snapshot()
        assert counters["jobs_poisoned_total"] == 1
        snap = sched.quarantine.snapshot()
        assert not snap["quarantined"] and snap["total_quarantined"] == 0, \
            "poison containment must refund every quarantine strike"
        from arrow_ballista_tpu.obs import journal

        pois = [e for e in journal.job_timeline(job_id)
                if e["kind"] == "job.poisoned"]
        assert pois
        (witnesses,) = pois[0]["attrs"]["evidence"].values()
        assert len(witnesses) >= 2
        _assert_lifecycle_leak_free(ctx)
        # fleet intact: the healthy query runs at full strength
        _frames_equal(ctx.sql(SQL).to_pandas(), baseline)
    finally:
        ctx._standalone.shutdown()
