"""KV cluster-state conformance suite, run over every backend.

Parity: the reference's reusable cluster tests (test_fuzz_reservations,
test_executor_registration, test_job_lifecycle) instantiate one generic
suite for each ClusterState/JobState backend
(reference ballista/scheduler/src/cluster/test/mod.rs:218-446, memory.rs:
484-560).  Here the backends are MemoryKv (in-process) and SqliteKv
(file-backed, multi-process safe — the sled analog).
"""
import json
import random
import threading
import time

import pytest

from arrow_ballista_tpu.scheduler.kv import (
    KvClusterState,
    KvJobStateBackend,
    MemoryKv,
    SqliteKv,
    TxnGuardFailed,
    open_store,
)
from arrow_ballista_tpu.scheduler.scheduler import SchedulerConfig, SchedulerServer
from arrow_ballista_tpu.scheduler.types import ExecutorHeartbeat, ExecutorMetadata

from .test_persistence import half_run_graph
from .test_scheduler import VirtualTaskLauncher


@pytest.fixture(params=["memory", "sqlite", "remote"])
def store(request, tmp_path):
    if request.param == "memory":
        s = MemoryKv()
        yield s
        s.close()
    elif request.param == "sqlite":
        s = SqliteKv(str(tmp_path / "state.db"))
        yield s
        s.close()
    else:
        # networked driver (the etcd-role service): full conformance over RPC
        from arrow_ballista_tpu.scheduler.kv_remote import KvServer, RemoteKv

        srv = KvServer()
        srv.start()
        s = RemoteKv(srv.host, srv.port)
        yield s
        s.close()
        srv.stop()


# --------------------------------------------------------------------------
# the trait itself
# --------------------------------------------------------------------------


def test_kv_basics(store):
    assert store.get("s", "k") is None
    store.put("s", "k", "v1")
    assert store.get("s", "k") == "v1"
    store.put("s", "k2", "v2")
    assert store.scan("s") == [("k", "v1"), ("k2", "v2")]
    assert store.scan("other") == []
    store.delete("s", "k")
    assert store.get("s", "k") is None


def test_kv_txn_guards(store):
    store.put("s", "a", "1")
    # guard holds: both ops apply atomically
    store.txn([("put", "s", "a", "2"), ("put", "s", "b", "x")],
              guards=[("s", "a", "1")])
    assert store.get("s", "a") == "2" and store.get("s", "b") == "x"
    # guard fails: nothing applies
    with pytest.raises(TxnGuardFailed):
        store.txn([("put", "s", "a", "99"), ("del", "s", "b", None)],
                  guards=[("s", "a", "not-current")])
    assert store.get("s", "a") == "2" and store.get("s", "b") == "x"
    # absent-guard (None) semantics
    store.txn([("put", "s", "fresh", "1")], guards=[("s", "fresh", None)])
    with pytest.raises(TxnGuardFailed):
        store.txn([("put", "s", "fresh", "2")], guards=[("s", "fresh", None)])


def test_kv_lock_contention_single_winner(store):
    # expired lock: exactly one of 8 concurrent contenders takes over
    store.put("locks", "jobz", json.dumps({"owner": "dead", "ts": time.time() - 999}))
    results = {}
    barrier = threading.Barrier(8)

    def contend(i):
        barrier.wait()
        results[i] = store.lock("locks", "jobz", f"owner-{i}", ttl_s=60.0)

    threads = [threading.Thread(target=contend, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(1 for ok in results.values() if ok) == 1
    winner = [i for i, ok in results.items() if ok][0]
    assert json.loads(store.get("locks", "jobz"))["owner"] == f"owner-{winner}"
    # held lock is not stealable, reentrant for the owner
    assert not store.lock("locks", "jobz", "someone-else", ttl_s=60.0)
    assert store.lock("locks", "jobz", f"owner-{winner}", ttl_s=60.0)


# --------------------------------------------------------------------------
# slot reservations are atomic under concurrency (the fuzz test)
# --------------------------------------------------------------------------


def test_fuzz_reservations(store):
    """N threads reserve/cancel against shared slots; slots never go
    negative and never exceed capacity (reference cluster/test/mod.rs:
    218-313)."""
    cluster = KvClusterState(store)
    capacity = {}
    for i in range(3):
        meta = ExecutorMetadata(f"e{i}", task_slots=4)
        cluster.register_executor(meta)
        capacity[f"e{i}"] = 4
    total_cap = sum(capacity.values())

    errors = []

    def hammer(seed):
        rng = random.Random(seed)
        for _ in range(40):
            n = rng.randint(1, 5)
            got = cluster.reserve_slots(n)
            if len(got) > n:
                errors.append(f"over-reserved: asked {n} got {len(got)}")
            avail = cluster.available_slots()
            if avail < 0 or avail > total_cap:
                errors.append(f"slots out of range: {avail}")
            time.sleep(rng.random() * 0.002)
            cluster.cancel_reservations(got)

    threads = [threading.Thread(target=hammer, args=(s,)) for s in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:5]
    # everything returned: full capacity free again
    assert cluster.available_slots() == total_cap
    # capacity clamp: freeing more than capacity can't overfill
    cluster.free_slots("e0", 99)
    assert cluster.available_slots() == total_cap


def test_executor_registration_and_expiry(store):
    cluster = KvClusterState(store)
    meta = ExecutorMetadata("e-reg", host="h", port=1, task_slots=2)
    cluster.register_executor(meta)
    assert cluster.get_executor("e-reg").host == "h"
    assert "e-reg" in cluster.alive_executors(60.0)
    cluster.save_heartbeat(ExecutorHeartbeat("e-reg", timestamp=time.time() - 999))
    assert "e-reg" not in cluster.alive_executors(60.0)
    assert "e-reg" in cluster.expired_executors(60.0)
    cluster.remove_executor("e-reg")
    assert cluster.get_executor("e-reg") is None


# --------------------------------------------------------------------------
# job state over the trait
# --------------------------------------------------------------------------


def test_job_lifecycle(store):
    backend = KvJobStateBackend(store)
    graph = half_run_graph()
    backend.save_job(graph)
    assert backend.list_jobs() == ["jobx"]
    loaded = backend.load_job("jobx")
    assert loaded.job_id == "jobx" and loaded.status == "running"
    assert backend.try_acquire_job("jobx", "sched-1")
    assert not backend.try_acquire_job("jobx", "sched-2")
    backend.remove_job("jobx")
    assert backend.list_jobs() == []
    # lock went with the job
    assert backend.try_acquire_job("jobx", "sched-2")


def test_two_scheduler_takeover_sqlite(tmp_path):
    """A sibling scheduler sharing the sqlite store adopts a dead
    scheduler's job and runs it to completion — the HA flow the KV
    backends exist for (reference try_acquire_job, cluster/mod.rs:347-350)."""
    url = f"sqlite:///{tmp_path}/cluster.db"
    store_a = open_store(url)
    backend_a = KvJobStateBackend(store_a)
    graph = half_run_graph()
    backend_a.save_job(graph)
    assert backend_a.try_acquire_job("jobx", "sched-dead")
    # sched-dead never renews; its lease goes stale
    time.sleep(0.05)

    store_b = open_store(url)
    backend_b = KvJobStateBackend(store_b)
    launcher = VirtualTaskLauncher()
    server = SchedulerServer(launcher, SchedulerConfig(), job_backend=backend_b,
                             scheduler_id="sched-new",
                             cluster_state=KvClusterState(store_b))
    launcher.scheduler = server
    server.init(start_reaper=False)
    try:
        server.register_executor(ExecutorMetadata("exec-B", task_slots=4))
        # fresh lease still held -> adoption refused
        assert server.recover_jobs() == []
        # expire the dead scheduler's lease, then adopt
        store_b.put("job_locks", "jobx",
                    json.dumps({"owner": "sched-dead", "ts": time.time() - 999}))
        assert server.recover_jobs() == ["jobx"]
        status = server.wait_for_job("jobx", 30)
        assert status.state == "successful"
        assert all(t.task.stage_id != 1 for _, t in launcher.launched)
        assert backend_b.load_job("jobx").status == "successful"
    finally:
        server.shutdown()
        store_a.close()
        store_b.close()


# --------------------------------------------------------------------------
# watch streams (reference KeyValueStore::watch, storage/mod.rs:30-147)
# --------------------------------------------------------------------------


def _await_event(w, pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        ev = w.get(timeout=0.5)
        if ev is not None and pred(ev):
            return ev
    return None


def test_watch_sees_puts_and_deletes(store):
    # mutations are spaced by event arrival: polling drivers (sqlite)
    # legitimately coalesce a rapid put+delete of the same key
    w = store.watch("ws", poll_interval_s=0.05)
    try:
        store.put("ws", "a", "1")
        ev = _await_event(w, lambda e: e.op == "put" and e.key == "a")
        assert ev is not None and ev.value == "1"
        store.put("ws", "b", "2")
        ev = _await_event(w, lambda e: e.op == "put" and e.key == "b")
        assert ev is not None and ev.value == "2"
        store.delete("ws", "a")
        assert _await_event(w, lambda e: e.op == "del" and e.key == "a") is not None
    finally:
        w.close()


def test_watch_is_scoped_to_keyspace(store):
    w = store.watch("only_this", poll_interval_s=0.05)
    try:
        store.put("other_space", "x", "1")
        store.put("only_this", "y", "2")
        deadline = time.time() + 5.0
        got = []
        while time.time() < deadline and not got:
            ev = w.get(timeout=0.5)
            if ev is not None:
                got.append(ev)
        assert got and got[0].key == "y"
        assert all(ev.space == "only_this" for ev in got)
    finally:
        w.close()


def test_remote_kv_two_clients_share_state_and_watch():
    """Two RemoteKv clients (two 'schedulers on different hosts') against
    one KV service: CAS atomicity + cross-client watch delivery."""
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer, RemoteKv

    srv = KvServer()
    srv.start()
    try:
        c1 = RemoteKv(srv.host, srv.port)
        c2 = RemoteKv(srv.host, srv.port)
        w = c2.watch("jobs")
        c1.put("jobs", "j1", "running")
        ev = w.get(timeout=5.0)
        assert ev is not None and ev.key == "j1" and ev.value == "running"
        # CAS conflict: c2's guard must observe c1's write
        with pytest.raises(TxnGuardFailed):
            c2.txn([("put", "jobs", "j1", "stolen")],
                   guards=[("jobs", "j1", None)])
        c2.txn([("put", "jobs", "j1", "done")],
               guards=[("jobs", "j1", "running")])
        assert c1.get("jobs", "j1") == "done"
        w.close()
    finally:
        srv.stop()


def test_remote_kv_backs_full_cluster_state():
    """KvClusterState + KvJobStateBackend run unmodified over the
    networked driver — the multi-host HA configuration."""
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer, RemoteKv

    srv = KvServer()
    srv.start()
    try:
        kv = RemoteKv(srv.host, srv.port)
        cs = KvClusterState(kv)
        cs.register_executor(ExecutorMetadata("e1", task_slots=2))
        res = cs.reserve_slots(3)
        assert len(res) == 2
        cs.free_slots("e1", 2)
        assert cs.available_slots() == 2

        jb = KvJobStateBackend(kv)
        assert jb.try_acquire_job("job1", "sched-a")
        assert not jb.try_acquire_job("job1", "sched-b")
    finally:
        srv.stop()


def test_scheduler_netservice_with_kv_url():
    """--cluster-backend kv://host:port connects the scheduler to the KV
    service (the HA deploy shape in deploy/docker-compose.yml)."""
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer, RemoteKv
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    srv = KvServer()
    srv.start()
    sched = None
    try:
        sched = SchedulerNetService("127.0.0.1", 0, rest_port=0,
                                    cluster_url=f"kv://{srv.host}:{srv.port}")
        sched.start()
        from arrow_ballista_tpu.scheduler.types import ExecutorMetadata

        sched.server.register_executor(ExecutorMetadata("kv-e1", task_slots=3))
        # the registration must be visible THROUGH the shared KV service
        peek = RemoteKv(srv.host, srv.port)
        assert peek.get("executors", "kv-e1") is not None
        assert peek.get("slots", "kv-e1") == "3"
    finally:
        if sched is not None:
            sched.stop()
        srv.stop()


def test_watch_close_wakes_blocked_iterator(store):
    done = []

    def consume(w):
        for _ in w:
            pass
        done.append(True)

    w = store.watch("idle_space", poll_interval_s=0.05)
    t = threading.Thread(target=consume, args=(w,))
    t.start()
    time.sleep(0.2)
    w.close()
    t.join(timeout=5.0)
    assert done, "blocked watch iterator did not terminate on close()"

def test_remote_watch_survives_kv_server_restart():
    """A KvServer bounce mid-watch must not kill the watch thread: the
    client reconnects with capped backoff, detects the head REGRESSION
    (the fresh server's sequence restarts at 0, which the server-side
    resync marker cannot flag — its replay log is empty), and resyncs:
    consumers see a 'resync' marker, the snapshot as puts, then live
    events again."""
    from arrow_ballista_tpu.scheduler.kv import MemoryKv
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer, RemoteKv

    backing = MemoryKv()
    srv = KvServer(backing)
    srv.start()
    host, port = srv.host, srv.port
    kv = RemoteKv(host, port)
    w = kv.watch("jobs")
    try:
        # advance the cursor well past where the restarted server's fresh
        # sequence will be, so the regression is unambiguous
        for i in range(3):
            kv.put("jobs", f"j{i}", "running")
        assert _await_event(w, lambda e: e.op == "put" and e.key == "j2") \
            is not None
        # bounce: same backing store (the persistent-backing restart
        # shape), same port, sequence counter reset to 0
        srv.stop()
        srv = KvServer(backing, host, port)
        srv.start()
        kv.put("jobs", "after", "1")
        assert _await_event(w, lambda e: e.op == "resync", timeout=10.0) \
            is not None, "watch did not resync after the server restart"
        assert _await_event(w, lambda e: e.op == "put" and e.key == "after",
                            timeout=10.0) is not None, \
            "watch dead after the server restart"
    finally:
        w.close()
        srv.stop()


def test_remote_watch_tolerates_down_server_at_creation():
    """Creating a watch while the KV service is down must not raise: the
    cursor acquisition happens inside the watch loop, which attaches (and
    primes the consumer with resync + snapshot) once the server is up."""
    from arrow_ballista_tpu.scheduler.kv import MemoryKv
    from arrow_ballista_tpu.scheduler.kv_remote import KvServer, RemoteKv

    backing = MemoryKv()
    srv = KvServer(backing)
    srv.start()
    host, port = srv.host, srv.port
    srv.stop()
    kv = RemoteKv(host, port)
    w = kv.watch("jobs")  # server is down: must not throw
    srv = KvServer(backing, host, port)
    srv.start()
    try:
        kv.put("jobs", "late", "1")
        assert _await_event(w, lambda e: e.op == "put" and e.key == "late",
                            timeout=10.0) is not None, \
            "watch never attached to the recovered server"
    finally:
        w.close()
        srv.stop()
