"""ICI-mesh shuffle + distributed aggregate on the virtual 8-device mesh.

Multi-chip coverage without a pod, mirroring how the reference tests
multi-node scheduling without a cluster (SURVEY.md §4).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from arrow_ballista_tpu.parallel import (
    PART_AXIS,
    distributed_filter_aggregate,
    distributed_grouped_aggregate,
    make_mesh,
    row_sharding,
    shuffle_rows,
)
from arrow_ballista_tpu.ops import kernels as K


N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEV
    return make_mesh(N_DEV)


def _place(mesh, arr):
    return jax.device_put(arr, row_sharding(mesh))


def test_shuffle_rows_preserves_multiset(mesh, rng):
    rows = 128 * N_DEV
    vals = rng.permutation(rows).astype(np.int64)  # unique, so routing is checkable
    dest = rng.integers(0, N_DEV, rows).astype(np.int32)
    mask = rng.random(rows) < 0.8

    cap = 128  # generous: per-device per-dest load ~16
    def per_shard(cols, d, m):
        rc, rm, ovf = shuffle_rows(cols, d, m, PART_AXIS, N_DEV, cap)
        return rc, rm, ovf

    from jax.sharding import PartitionSpec as P
    fn = jax.jit(jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=({"v": P(PART_AXIS)}, P(PART_AXIS), P(PART_AXIS)),
        out_specs=({"v": P(PART_AXIS)}, P(PART_AXIS), P(PART_AXIS))))
    rc, rm, ovf = fn({"v": _place(mesh, vals)}, _place(mesh, dest),
                     _place(mesh, mask))
    assert not np.any(np.asarray(ovf))
    got = np.sort(np.asarray(rc["v"])[np.asarray(rm)])
    want = np.sort(vals[mask])
    np.testing.assert_array_equal(got, want)

    # routing: rows for destination d actually land on shard d
    rm_np = np.asarray(rm).reshape(N_DEV, -1)
    rv_np = np.asarray(rc["v"]).reshape(N_DEV, -1)
    val_to_dest = {int(v): int(d) for v, d, m in zip(vals, dest, mask) if m}
    for shard in range(N_DEV):
        for v in rv_np[shard][rm_np[shard]]:
            assert val_to_dest[int(v)] == shard


def test_shuffle_overflow_flag(mesh):
    rows = 64 * N_DEV
    vals = np.arange(rows, dtype=np.int64)
    dest = np.zeros(rows, dtype=np.int32)  # all rows to device 0
    mask = np.ones(rows, dtype=bool)

    from jax.sharding import PartitionSpec as P
    def per_shard(cols, d, m):
        return shuffle_rows(cols, d, m, PART_AXIS, N_DEV, 8)

    fn = jax.jit(jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=({"v": P(PART_AXIS)}, P(PART_AXIS), P(PART_AXIS)),
        out_specs=({"v": P(PART_AXIS)}, P(PART_AXIS), P(PART_AXIS))))
    _, _, ovf = fn({"v": _place(mesh, vals)}, _place(mesh, dest),
                   _place(mesh, mask))
    assert np.any(np.asarray(ovf))


def test_distributed_aggregate_matches_single_device(mesh, rng):
    rows = 512 * N_DEV
    g = rng.integers(0, 23, rows).astype(np.int64)
    x = rng.integers(1, 100, rows).astype(np.int64)
    mask = rng.random(rows) < 0.9

    run = distributed_grouped_aggregate(
        mesh, ["g"], [("x", "sum"), ("x", "count"), ("x", "min")],
        partial_capacity=64, final_capacity=16)
    fk, fv, fm, ovf = run({"g": _place(mesh, g), "x": _place(mesh, x)},
                          _place(mesh, mask))
    assert not bool(np.asarray(ovf).any())
    fm = np.asarray(fm)
    keys = np.asarray(fk[0])[fm]
    sums = np.asarray(fv[0])[fm]
    counts = np.asarray(fv[1])[fm]
    mins = np.asarray(fv[2])[fm]

    assert len(keys) == len(np.unique(g[mask]))
    for k in np.unique(g[mask]):
        sel = (g == k) & mask
        i = np.where(keys == k)[0]
        assert len(i) == 1, f"group {k} appears {len(i)} times"
        assert sums[i[0]] == x[sel].sum()
        assert counts[i[0]] == sel.sum()
        assert mins[i[0]] == x[sel].min()


def test_distributed_filter_aggregate_q1_shape(mesh, rng):
    """A q1-shaped fused step: filter + derived column + 2-key group-by."""
    rows = 256 * N_DEV
    flag = rng.integers(0, 3, rows).astype(np.int64)
    status = rng.integers(0, 2, rows).astype(np.int64)
    qty = rng.integers(1, 50, rows).astype(np.float64)
    price = rng.random(rows).astype(np.float64) * 1000
    ship = rng.integers(0, 2500, rows).astype(np.int32)
    mask = np.ones(rows, dtype=bool)

    cutoff = 2000

    def filt(cols, m):
        keep = m & (cols["ship"] <= cutoff)
        cols = dict(cols)
        cols["disc_price"] = cols["price"] * 0.95
        return cols, keep

    run = distributed_filter_aggregate(
        mesh, filt, ["flag", "status"],
        [("qty", "sum"), ("disc_price", "sum"), ("qty", "count")],
        partial_capacity=16, final_capacity=8)
    fk, fv, fm, ovf = run(
        {"flag": _place(mesh, flag), "status": _place(mesh, status),
         "qty": _place(mesh, qty), "price": _place(mesh, price),
         "ship": _place(mesh, ship)},
        _place(mesh, mask))
    assert not bool(np.asarray(ovf).any())
    fm = np.asarray(fm)
    kf, ks = np.asarray(fk[0])[fm], np.asarray(fk[1])[fm]
    sq = np.asarray(fv[0])[fm]

    keep = ship <= cutoff
    seen = set()
    for f, s in zip(kf, ks):
        seen.add((int(f), int(s)))
        sel = keep & (flag == f) & (status == s)
        i = np.where((kf == f) & (ks == s))[0]
        np.testing.assert_allclose(sq[i[0]], qty[sel].sum())
    want = {(int(f), int(s)) for f, s in zip(flag[keep], status[keep])}
    assert seen == want


def test_distributed_aggregate_at_scale_with_skew(mesh, rng):
    """VERDICT r4 #9: the mesh step at >=100k rows/device, at a distinct-key
    volume where the capacity-factor state exchange overflows at a tight
    factor and the retry ladder (bigger factor) succeeds — the same
    host-retry mechanism ops/mesh_exec.py / parallel/ici_shuffle.py run."""
    rows_per_dev = 131_072
    rows = rows_per_dev * N_DEV
    n_groups = 60_000
    g = rng.integers(0, n_groups, rows).astype(np.int64)
    # size skew on top: ~25% of rows pile into group 0
    g = np.where(rng.random(rows) < 0.25, 0, g)
    x = rng.integers(1, 50, rows).astype(np.int64)
    mask = rng.random(rows) < 0.95

    # tight capacity factor: each device emits up to ~60k/8 distinct-key
    # states per bucket, far above cap = partial/8 * 0.5
    tight = distributed_grouped_aggregate(
        mesh, ["g"], [("x", "sum"), ("x", "count")],
        partial_capacity=1 << 16, final_capacity=1 << 14, skew_factor=0.5)
    _, _, _, ovf = tight({"g": _place(mesh, g), "x": _place(mesh, x)},
                         _place(mesh, mask))
    assert bool(np.asarray(ovf).any()), "tight factor did not overflow"

    run = distributed_grouped_aggregate(
        mesh, ["g"], [("x", "sum"), ("x", "count")],
        partial_capacity=1 << 16, final_capacity=1 << 14, skew_factor=2.0)
    fk, fv, fm, ovf = run({"g": _place(mesh, g), "x": _place(mesh, x)},
                          _place(mesh, mask))
    assert not bool(np.asarray(ovf).any())
    fm_np = np.asarray(fm)
    keys = np.asarray(fk[0])[fm_np]
    sums = np.asarray(fv[0])[fm_np]
    counts = np.asarray(fv[1])[fm_np]
    assert len(keys) == len(np.unique(g[mask]))
    # exact check on the skewed group and two tail groups
    uniq = np.unique(g[mask])
    for k in (0, int(uniq[1]), int(uniq[-1])):
        sel = (g == k) & mask
        i = np.where(keys == k)[0]
        assert len(i) == 1
        assert sums[i[0]] == x[sel].sum()
        assert counts[i[0]] == sel.sum()
