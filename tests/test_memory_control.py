"""Memory control: bounded-footprint execution under a per-task budget.

The reference's answer to memory pressure is reactive spill
(reference ballista/core/src/utils.rs:176-212 write_stream_to_disk);
a static-shape TPU engine cannot realloc or spill mid-kernel, so the
budget (``ballista.memory.task.budget.bytes``) is enforced BEFORE
allocation: joins run their probe loop in bounded windows, and 'auto'
shuffle partition counts scale so planned task inputs fit.  Disk-tier
state remains the shuffle's IPC files (the same role the reference's
shuffle files play as data checkpoints).
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu import BallistaConfig, Field, INT64, Schema
from arrow_ballista_tpu.models import expr as E
from arrow_ballista_tpu.ops.operators import JoinExec
from arrow_ballista_tpu.ops.physical import MemoryScanExec, TaskContext
from arrow_ballista_tpu.utils.config import (
    MEM_TASK_BUDGET,
    resolve_task_budget,
)

SCHEMA_F = Schema([Field("k", INT64), Field("v", INT64)])
SCHEMA_D = Schema([Field("dk", INT64), Field("w", INT64)])


def _tables(n_fact=30_000, n_dim=500, dup=3, seed=11):
    rng = np.random.default_rng(seed)
    fact = pa.table({
        "k": rng.integers(0, n_dim * 2, n_fact).astype(np.int64),
        "v": rng.integers(0, 1000, n_fact).astype(np.int64),
    })
    # duplicate dim keys -> fan-out > 1 so expansion buffers matter
    dk = np.repeat(np.arange(n_dim, dtype=np.int64), dup)
    dim = pa.table({
        "dk": dk,
        "w": np.arange(len(dk), dtype=np.int64),
    })
    return fact, dim


def _join(join_type, budget=None):
    fact, dim = _tables()
    left = MemoryScanExec(SCHEMA_F, fact, 1)
    right = MemoryScanExec(SCHEMA_D, dim, 1)
    dist = "partitioned" if join_type == "full" else "broadcast"
    j = JoinExec(left, right, [(E.Column("k"), E.Column("dk"))],
                 join_type=join_type, dist=dist)
    cfg = {} if budget is None else {MEM_TASK_BUDGET: str(budget)}
    ctx = TaskContext(config=BallistaConfig(cfg), job_id="jmem")
    batches = j.execute(0, ctx)
    frames = [b.to_pandas() for b in batches if b.num_rows]
    df = pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()
    return j, df


@pytest.mark.parametrize("join_type", ["inner", "semi", "anti"])
def test_chunked_join_matches_single_pass(join_type):
    _, unlimited = _join(join_type)
    j, budgeted = _join(join_type, budget=200_000)  # ~0.2 MB forces windows
    chunks = j.metrics().to_dict().get("join_probe_chunks", 0)
    assert chunks > 1, "budget did not engage the windowed probe loop"
    sort_cols = list(unlimited.columns)
    a = unlimited.sort_values(sort_cols).reset_index(drop=True)
    b = budgeted.sort_values(sort_cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)


@pytest.mark.parametrize("join_type", ["full", "left"])
def test_outer_joins_keep_single_pass(join_type):
    """full: unmatched-build needs all-probe hit accumulation; left: the
    miss-append block is probe-capacity-sized per window, so windowing
    would multiply memory instead of bounding it."""
    _, unlimited = _join(join_type)
    j, budgeted = _join(join_type, budget=200_000)
    assert j.metrics().to_dict().get("join_probe_chunks", 0) == 0
    sort_cols = list(unlimited.columns)
    pd.testing.assert_frame_equal(
        unlimited.sort_values(sort_cols).reset_index(drop=True),
        budgeted.sort_values(sort_cols).reset_index(drop=True))


def test_budget_resolution():
    assert resolve_task_budget(BallistaConfig({MEM_TASK_BUDGET: "0"})) == 0
    assert resolve_task_budget(BallistaConfig({MEM_TASK_BUDGET: "1048576"})) == 1 << 20
    # auto on the CPU test backend: unlimited
    assert resolve_task_budget(BallistaConfig()) == 0


def test_auto_partitions_scale_with_budget():
    """A 100M-row x 17-byte table under a 64 MB task budget needs ~27
    partitions more than the 64-cap would ever grant at batch=16M."""
    from arrow_ballista_tpu.catalog import SchemaCatalog, TableProvider
    from arrow_ballista_tpu.models import logical as L
    from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner

    class BigTable(TableProvider):
        name = "big"
        schema = SCHEMA_F

        def scan(self, projection, filters, target_partitions):
            raise NotImplementedError

        def row_count(self):
            return 100_000_000

    cat = SchemaCatalog()
    cat.register(BigTable())
    scan = L.TableScan("big", SCHEMA_F)
    base_cfg = BallistaConfig({"ballista.shuffle.partitions": "auto",
                               "ballista.batch.size": str(1 << 24)})
    p = PhysicalPlanner(cat, base_cfg)
    p._resolve_auto_partitions(scan)
    unbounded = p.partitions
    assert unbounded <= 64
    cfg = BallistaConfig({"ballista.shuffle.partitions": "auto",
                          "ballista.batch.size": str(1 << 24),
                          MEM_TASK_BUDGET: str(64 << 20)})
    p2 = PhysicalPlanner(cat, cfg)
    p2._resolve_auto_partitions(scan)
    assert p2.partitions > unbounded
    assert p2.partitions <= 256
    # a task's planned input now fits the budget
    assert 100_000_000 * 17 / p2.partitions <= (64 << 20)


def test_q9_class_query_under_capped_budget(tmp_path):
    """VERDICT r4 #6 done-criterion (scaled): a multi-join + group-by
    (q9-shaped) completes under an artificially capped memory budget and
    matches the unlimited run."""
    import pyarrow.parquet as pq

    from arrow_ballista_tpu.client.context import BallistaContext

    rng = np.random.default_rng(23)
    n = 60_000
    pq.write_table(pa.table({
        "pk": rng.integers(0, 2000, n).astype(np.int64),
        "sk": rng.integers(0, 100, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
    }), str(tmp_path / "li.parquet"), row_group_size=10_000)
    pq.write_table(pa.table({
        "pk": np.arange(2000, dtype=np.int64),
        "grp": np.array(["g%d" % (i % 12) for i in range(2000)]),
    }), str(tmp_path / "part.parquet"))
    pq.write_table(pa.table({
        "sk": np.arange(100, dtype=np.int64),
        "nat": np.array(["n%d" % (i % 7) for i in range(100)]),
    }), str(tmp_path / "supp.parquet"))

    q = ("select p.grp, s.nat, count(*) as n, sum(l.qty) as q "
         "from li l join part p on l.pk = p.pk "
         "join supp s on l.sk = s.sk "
         "group by p.grp, s.nat order by p.grp, s.nat")

    def run(budget):
        cfg = {"ballista.shuffle.partitions": "4",
               "ballista.join.broadcast_threshold": "10"}  # force partitioned
        if budget:
            cfg[MEM_TASK_BUDGET] = str(budget)
        ctx = BallistaContext.standalone(BallistaConfig(cfg),
                                         concurrent_tasks=2)
        ctx.register_parquet("li", str(tmp_path / "li.parquet"))
        ctx.register_parquet("part", str(tmp_path / "part.parquet"))
        ctx.register_parquet("supp", str(tmp_path / "supp.parquet"))
        out = ctx.sql(q).to_pandas()
        ctx.shutdown()
        return out

    unlimited = run(None)
    capped = run(300_000)
    pd.testing.assert_frame_equal(unlimited, capped)
