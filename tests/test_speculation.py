"""Speculative execution: policy math, attempt-dedup races, monitor e2e.

The policy functions are pure and tested directly; the attempt machinery
is driven on a bare ExecutionGraph (reference execution_graph.rs test
style — fabricated completions, no executors); the final test runs the
real speculation monitor against a virtual cluster where one task is
swallowed by its "host" and must be rescued by a duplicate attempt.
"""
import time

import pytest

from arrow_ballista_tpu.scheduler.execution_graph import (
    RUNNING,
    SUCCESSFUL,
    ExecutionGraph,
)
from arrow_ballista_tpu.scheduler.scheduler import (
    SchedulerConfig,
    SchedulerServer,
)
from arrow_ballista_tpu.scheduler.speculation import (
    SpeculationPolicy,
    find_candidates,
    speculation_cutoff_s,
)
from arrow_ballista_tpu.scheduler.types import (
    IO_ERROR,
    ExecutorMetadata,
    FailedReason,
    TaskStatus,
)

from .test_scheduler import (
    VirtualTaskLauncher,
    drain,
    fake_success,
    physical_plan,
    run_job,
)


# --------------------------------------------------------------------------
# policy math
# --------------------------------------------------------------------------

def test_cutoff_none_without_baseline():
    assert speculation_cutoff_s([], 0.75, 1.5, 5.0) is None, \
        "no completed attempts -> no cutoff (never speculate blind)"


def test_cutoff_nearest_rank_quantile():
    # q=0.75 over 4 samples -> 3rd smallest (nearest-rank), scaled by 2x
    assert speculation_cutoff_s([1.0, 2.0, 3.0, 4.0], 0.75, 2.0, 0.0) \
        == pytest.approx(6.0)
    # single sample: the quantile IS that sample
    assert speculation_cutoff_s([2.0], 0.75, 1.5, 0.0) == pytest.approx(3.0)


def test_cutoff_min_runtime_floor():
    # sub-millisecond baselines must not trigger hair-trigger duplicates
    assert speculation_cutoff_s([0.001, 0.002], 0.75, 1.5, 5.0) \
        == pytest.approx(5.0)


def test_cutoff_quantile_clamped():
    assert speculation_cutoff_s([1.0, 2.0], 9.0, 1.0, 0.0) == pytest.approx(2.0)
    assert speculation_cutoff_s([1.0, 2.0], -1.0, 1.0, 0.0) == pytest.approx(1.0)


def test_find_candidates_cutoff_budget_and_dedup():
    graph = ExecutionGraph.build("j", physical_plan(partitions=4))
    tasks = {}
    for _ in range(4):
        t = graph.pop_next_task("exec-A")
        tasks[t.task.partition] = t
    # partitions 1-3 complete fast and form the duration baseline;
    # partition 0 keeps running
    for p in (1, 2, 3):
        graph.update_task_status([fake_success(tasks[p], "exec-A")])
    stage = graph.stages[1]
    assert stage.state == RUNNING and len(stage.durations) == 3
    policy = SpeculationPolicy(enabled=True, quantile=0.5, multiplier=1.0,
                               min_runtime_s=1.0, max_concurrent=1)
    started = stage.task_infos[0].started_at
    assert find_candidates(graph, started + 0.5, policy) == [], \
        "younger than the cutoff: not a straggler"
    assert find_candidates(graph, started + 1.5, policy) \
        == [(1, 0, "exec-A")]
    # an in-flight duplicate removes the candidate AND spends the budget
    assert graph.launch_speculative(1, 0, "exec-B") is not None
    assert find_candidates(graph, started + 1.5, policy) == []


# --------------------------------------------------------------------------
# attempt-dedup races on the graph
# --------------------------------------------------------------------------

def test_launch_speculative_guards():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    assert graph.launch_speculative(1, 0, "exec-B") is None, "nothing running"
    t = graph.pop_next_task("exec-A")
    p = t.task.partition
    assert graph.launch_speculative(1, p, "exec-A") is None, \
        "a duplicate on the SAME host cannot dodge that host's slowness"
    spec = graph.launch_speculative(1, p, "exec-B")
    assert spec is not None and spec.task.speculative
    assert spec.task.task_attempt != t.task.task_attempt
    assert graph.launch_speculative(1, p, "exec-C") is None, \
        "one duplicate per partition"
    graph.update_task_status([fake_success(t, "exec-A")])
    assert graph.launch_speculative(1, p, "exec-B") is None, "already finished"


def test_primary_win_cancels_speculative_loser():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    p = t.task.partition
    spec = graph.launch_speculative(1, p, "exec-B")
    events = graph.update_task_status([fake_success(t, "exec-A")])
    assert not any(k == "speculative_win" for k, _ in events)
    cancels = [payload for kind, payload in events if kind == "cancel_task"]
    assert len(cancels) == 1
    executor_id, tid = cancels[0]
    assert executor_id == "exec-B"
    assert tid.task_attempt == spec.task.task_attempt and tid.speculative
    stage = graph.stages[1]
    assert stage.task_infos[p].state == "success"
    assert stage.task_infos[p].attempt == t.task.task_attempt
    assert p not in stage.speculative_tasks
    assert len(stage.durations) == 1, "winner's duration feeds the baseline"
    # the loser's late success must not disturb the recorded outputs
    before = dict(stage.outputs)
    assert graph.update_task_status([fake_success(spec, "exec-B")]) == []
    assert stage.outputs == before
    assert stage.task_infos[p].attempt == t.task.task_attempt


def test_speculative_win_cancels_primary_loser():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    p = t.task.partition
    spec = graph.launch_speculative(1, p, "exec-B")
    events = graph.update_task_status([fake_success(spec, "exec-B")])
    assert ("speculative_win", (1, p)) in events
    cancels = [payload for kind, payload in events if kind == "cancel_task"]
    assert len(cancels) == 1
    executor_id, tid = cancels[0]
    assert executor_id == "exec-A"
    assert tid.task_attempt == t.task.task_attempt and not tid.speculative
    stage = graph.stages[1]
    assert stage.task_infos[p].state == "success"
    assert stage.task_infos[p].attempt == spec.task.task_attempt
    assert p not in stage.speculative_tasks
    # the cancelled primary unwinds as killed: bookkeeping only, no reset
    graph.update_task_status([TaskStatus(t.task, "exec-A", "killed")])
    assert stage.task_infos[p].state == "success"
    # exactly one terminal success per partition in the attempt log
    wins = [e for e in stage.attempt_log
            if e["partition"] == p and e["state"] == "success"]
    assert len(wins) == 1 and wins[0]["speculative"]
    drain(graph, "exec-B")
    assert graph.status == "successful"


def test_speculative_failure_is_a_free_drop():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    p = t.task.partition
    spec = graph.launch_speculative(1, p, "exec-B")
    events = graph.update_task_status([TaskStatus(
        spec.task, "exec-B", "failed",
        failure=FailedReason(IO_ERROR, "duplicate died"))])
    assert events == []
    stage = graph.stages[1]
    assert stage.task_failures[p] == 0, \
        "a dead duplicate must not charge the primary's retry budget"
    assert p not in stage.speculative_tasks
    assert stage.task_infos[p].state == "running", "primary unaffected"
    graph.update_task_status([fake_success(t, "exec-A")])
    drain(graph, "exec-A")
    assert graph.status == "successful"


def test_primary_failure_promotes_speculative():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    p = t.task.partition
    spec = graph.launch_speculative(1, p, "exec-B")
    graph.update_task_status([TaskStatus(
        t.task, "exec-A", "failed",
        failure=FailedReason(IO_ERROR, "primary died"))])
    stage = graph.stages[1]
    info = stage.task_infos[p]
    assert info is not None and info.state == "running"
    assert info.attempt == spec.task.task_attempt and info.speculative, \
        "the in-flight duplicate is promoted instead of a third launch"
    assert p not in stage.speculative_tasks
    # the promoted attempt's success completes the partition (no
    # speculative_win: it IS the primary now)
    events = graph.update_task_status([fake_success(spec, "exec-B")])
    assert not any(k in ("speculative_win", "cancel_task")
                   for k, _ in events)
    assert stage.task_infos[p].state == "success"


def test_executor_lost_promotes_surviving_speculative():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    p = t.task.partition
    spec = graph.launch_speculative(1, p, "exec-B")
    graph.executor_lost("exec-A")
    stage = graph.stages[1]
    info = stage.task_infos[p]
    assert info is not None and info.executor_id == "exec-B" \
        and info.speculative
    assert p not in stage.speculative_tasks
    graph.update_task_status([fake_success(spec, "exec-B")])
    assert stage.task_infos[p].state == "success"
    drain(graph, "exec-B")
    assert graph.status == "successful"


def test_rollback_forgets_speculative_duplicates():
    graph = ExecutionGraph.build("j", physical_plan(partitions=2))
    t = graph.pop_next_task("exec-A")
    graph.launch_speculative(1, t.task.partition, "exec-B")
    stage = graph.stages[1]
    assert stage.speculative_tasks
    stage.rollback()
    assert not stage.speculative_tasks
    # late statuses from the rolled-back epoch are dropped entirely
    assert graph.update_task_status([fake_success(t, "exec-A")]) == []
    assert all(i is None for i in stage.task_infos)


# --------------------------------------------------------------------------
# the real monitor against a virtual cluster
# --------------------------------------------------------------------------

class StragglerLauncher(VirtualTaskLauncher):
    """Answers every task instantly EXCEPT the first attempt of stage-1
    partition 0, which it swallows — a task stuck on a sick host that will
    never report.  Records task-level cancels."""

    def __init__(self):
        super().__init__()
        self.swallowed = []
        self.cancelled_tasks = []

    def launch_tasks(self, executor_id, tasks):
        report = []
        with self._lock:
            self.launched.extend((executor_id, t) for t in tasks)
        for t in tasks:
            tid = t.task
            if tid.stage_id == 1 and tid.partition == 0 \
                    and tid.task_attempt == 0:
                self.swallowed.append((executor_id, tid))
                continue
            report.append(fake_success(t, executor_id))
        if report:
            self.scheduler.update_task_status(executor_id, report)

    def cancel_task(self, executor_id, task):
        self.cancelled_tasks.append((executor_id, task))


def test_monitor_rescues_swallowed_task():
    launcher = StragglerLauncher()
    server = SchedulerServer(launcher, SchedulerConfig(
        task_distribution="round-robin",
        speculation_enabled=True, speculation_quantile=0.5,
        speculation_multiplier=1.0, speculation_min_runtime_s=0.2,
        speculation_max_concurrent=2, speculation_interval_s=0.05))
    launcher.scheduler = server
    server.init(start_reaper=False)
    for i in range(2):
        server.register_executor(ExecutorMetadata(f"exec-{i}", task_slots=4))
    try:
        status = run_job(server, physical_plan())
        assert status.state == "successful", status.error
        assert len(launcher.swallowed) == 1
        stuck_executor, stuck_tid = launcher.swallowed[0]
        spec_launches = [(eid, t) for eid, t in launcher.launched
                         if t.task.speculative]
        assert len(spec_launches) == 1, \
            "exactly one duplicate for the one straggler"
        spec_executor, spec_task = spec_launches[0]
        assert spec_executor != stuck_executor, \
            "the duplicate must land on a DIFFERENT executor"
        assert spec_task.task.stage_id == 1 and spec_task.task.partition == 0
        # first result wins: the stuck primary is told to die (the cancel
        # is dispatched off the event loop — poll briefly for delivery)
        deadline = time.monotonic() + 5.0
        while (stuck_executor, stuck_tid) not in launcher.cancelled_tasks \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert (stuck_executor, stuck_tid) in launcher.cancelled_tasks
        text = server.metrics.gather()
        assert "speculative_tasks_launched_total 1" in text
        assert "speculative_wins_total 1" in text
        graph = server.jobs.get_graph("job1")
        log = graph.stages[1].attempt_log
        assert any(e["speculative"] and e["state"] == "success" for e in log)
        assert graph.stages[1].state == SUCCESSFUL
    finally:
        server.shutdown()


def test_monitor_not_started_when_disabled():
    launcher = VirtualTaskLauncher()
    server = SchedulerServer(launcher, SchedulerConfig())
    launcher.scheduler = server
    server.init(start_reaper=False)
    try:
        assert server._spec_monitor is None, \
            "speculation off (the default) must add no background work"
        assert not server.config.speculation.enabled
    finally:
        server.shutdown()


# --------------------------------------------------------------------------
# speculation x adaptive execution (ISSUE 7 regression)
# --------------------------------------------------------------------------

def test_speculative_loser_after_consumer_coalesce():
    """A speculative duplicate still in flight when its stage completes —
    and the CONSUMER stage then gets AQE-coalesced — must neither
    double-count outputs when its late status lands nor wedge the attempt
    bookkeeping."""
    from arrow_ballista_tpu.ops.shuffle import ShuffleWritePartition
    from arrow_ballista_tpu.scheduler.aqe import AqePolicy

    def sized(task, executor_id):
        writes = [ShuffleWritePartition(
            q, f"/fake/j/1/{task.task.partition}/data-{q}.arrow", 100, 100)
            for q in range(task.plan.partitioning.count)]
        return TaskStatus(task.task, executor_id, "success",
                          shuffle_writes=writes)

    graph = ExecutionGraph.build("j", physical_plan(partitions=8))
    graph.aqe = AqePolicy(coalesce_target_rows=1700, coalesce_target_bytes=0,
                          skew_enabled=False, broadcast_enabled=False)
    tasks = [graph.pop_next_task("exec-A") for _ in range(8)]
    assert all(t is not None and t.task.stage_id == 1 for t in tasks)
    # everything but the last partition completes; the straggler gets a
    # speculative duplicate on another executor
    for t in tasks[:-1]:
        graph.update_task_status([sized(t, "exec-A")])
    straggler = tasks[-1]
    spec = graph.launch_speculative(1, straggler.task.partition, "exec-B")
    assert spec is not None

    # the primary wins; stage 1 completes; stage 2 resolves AND coalesces
    # 8 -> 4 with the duplicate still in flight
    events = graph.update_task_status([sized(straggler, "exec-A")])
    stage1, stage2 = graph.stages[1], graph.stages[2]
    assert stage1.state == SUCCESSFUL
    assert stage2.state == RUNNING and stage2.partitions == 4
    cancels = [payload for kind, payload in events if kind == "cancel_task"]
    assert any(tid.task_attempt == spec.task.task_attempt
               for _eid, tid in cancels), "the loser must be cancelled"

    # the loser's late success arrives AFTER the consumer was rewritten:
    # dropped entirely — outputs, rewrite, and attempt log all unchanged
    before_outputs = dict(stage1.outputs)
    before_rewrites = list(stage2.aqe_rewrites)
    assert graph.update_task_status([sized(spec, "exec-B")]) == []
    assert stage1.outputs == before_outputs
    assert stage2.aqe_rewrites == before_rewrites
    assert stage2.partitions == 4
    p = straggler.task.partition
    assert stage1.task_infos[p].attempt == straggler.task.task_attempt
    assert p not in stage1.speculative_tasks
    # attempt ids stay monotonic: primary + duplicate = two draws
    assert stage1.task_attempts[p] == 2
    # the audit log records BOTH attempts' terminal states, exactly once
    # each — no duplicated or dangling entries after the rewrite
    entries = [e for e in stage1.attempt_log if e["partition"] == p]
    assert len(entries) == 2
    assert {e["attempt"] for e in entries} \
        == {straggler.task.task_attempt, spec.task.task_attempt}
    assert all(e["state"] != "running" for e in entries)
    drain(graph, "exec-A")
    assert graph.status == "successful"
