"""Whole-stage compilation: chain detection, policy verdicts, fused
execution, serde, observability convergence, and interplay with the
adaptive-execution machinery.

Layers, matching how the subsystem is built:

  1. chain detection (compile/chains.py): the ONE candidate finder the
     advisor and the compiler share — plan-walk and operator_tree views
     must agree, and the structural fingerprint must be stable across
     equal chains and sensitive to real differences;
  2. policy + verdicts (compile/fuse.py): config parsing, the
     conservative per-instance allowlist (host mode, scalar subqueries,
     non-partial aggregates, clustered annotations), and the
     agg-heads-only run splitting;
  3. fused execution (compile/fused.py): a FusedStageExec's output is
     bit-identical to the interpreted chain it replaced, for row-only
     and aggregate-headed chains, with the runtime fallback latch;
  4. serde: fused plan nodes round-trip the wire; graph checkpoints
     carry fusion records;
  5. e2e (standalone): fusion on vs off produces identical results, the
     stage records the rewrite, EXPLAIN ANALYZE shows the fused kernel,
     the advisor marks chains fused vs merely advised, and the doctor's
     fusion-missed rule fires only above its savings threshold;
  6. interplay: lineage rollback re-resolves and re-fuses (without
     double-wrapping), speculative duplicates ship the same fused plan,
     and AQE rewrites validate against fused stages.
"""
import json

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from arrow_ballista_tpu import Field, INT64, Schema, serde
from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.compile.chains import (
    STATIC_REASONS,
    UNFUSABLE,
    chain_fingerprint,
    dict_chains,
    plan_chains,
    walk_plan_paths,
)
from arrow_ballista_tpu.compile.fuse import (
    CompilePolicy,
    _op_verdict,
    _split_runs,
    fuse_stage,
)
from arrow_ballista_tpu.compile.fused import FusedStageExec
from arrow_ballista_tpu.models import expr as E
from arrow_ballista_tpu.ops import operators as O
from arrow_ballista_tpu.ops.physical import (
    MemoryScanExec,
    MetricsSet,
    TaskContext,
    schema_sig,
)
from arrow_ballista_tpu.utils.config import BallistaConfig
from arrow_ballista_tpu.utils.errors import InternalError

from .test_scheduler import drain, physical_plan


# --------------------------------------------------------------------------
# plumbing
# --------------------------------------------------------------------------

def _scan(n=100, partitions=2):
    t = pa.table({"x": pa.array(np.arange(n, dtype=np.int64)),
                  "y": pa.array((np.arange(n, dtype=np.int64) * 3) % 7)})
    schema = Schema([Field("x", INT64), Field("y", INT64)])
    return MemoryScanExec(schema, t, partitions, [])


def _chain(n=100, partitions=2):
    """scan -> filter -> projection, returned head-first."""
    scan = _scan(n, partitions)
    filt = O.FilterExec(scan, E.BinOp(">", E.Column("x"), E.Lit(10)))
    proj = O.ProjectionExec(
        filt, [(E.BinOp("*", E.Column("x"), E.Lit(2)), "xx"),
               (E.Column("y"), "y")])
    return proj, filt, scan


def _ctx():
    return TaskContext(config=BallistaConfig(), job_id="test-compile")


def _rows(batches):
    """Sorted materialized rows, null-masked, for exact comparison."""
    out = []
    for b in batches:
        tbl = b.to_arrow()
        out.extend(sorted(map(str, tbl.to_pylist())))
    return sorted(out)


def _graph(sql=None, partitions=4, enabled=True, min_ops=2):
    from arrow_ballista_tpu.compile.fuse import fuse_resolved_stages
    from arrow_ballista_tpu.scheduler.execution_graph import ExecutionGraph

    graph = ExecutionGraph.build("job-fuse", physical_plan(sql, partitions))
    graph.compiler = CompilePolicy(enabled=enabled, min_ops=min_ops)
    fuse_resolved_stages(graph)
    return graph


def _fused_nodes(plan):
    out = []

    def walk(p):
        if isinstance(p, FusedStageExec):
            out.append(p)
        for c in p.children():
            walk(c)

    walk(plan)
    return out


# --------------------------------------------------------------------------
# 1. chain detection
# --------------------------------------------------------------------------

def test_plan_walk_paths_match_metric_convention():
    proj, filt, scan = _chain()
    writer_free = proj  # stage plans root at a writer; walk any subtree
    paths = walk_plan_paths(writer_free)
    assert [(p, type(n).__name__) for p, n in paths] == [
        ("0", "ProjectionExec"), ("0.0", "FilterExec"),
        ("0.0.0", "MemoryScanExec")]


def test_plan_and_dict_chains_agree():
    proj, filt, scan = _chain()
    pc = plan_chains(proj)
    tree = [{"path": p, "op": type(n).__name__}
            for p, n in walk_plan_paths(proj)]
    dc = dict_chains(tree)
    assert [[type(n).__name__ for _p, n in c] for c in pc] \
        == [[op["op"] for op in c] for c in dc]
    # the chain covers the whole single-child pipeline
    assert [[type(n).__name__ for _p, n in c] for c in pc] == [
        ["ProjectionExec", "FilterExec", "MemoryScanExec"]]


def test_chains_break_at_unfusable_and_multi_child():
    assert "ShuffleReaderExec" in UNFUSABLE
    tree = [
        {"path": "0", "op": "ShuffleWriterExec"},
        {"path": "0.0", "op": "ProjectionExec"},
        {"path": "0.0.0", "op": "JoinExec"},
        {"path": "0.0.0.0", "op": "FilterExec"},
        {"path": "0.0.0.0.0", "op": "ShuffleReaderExec"},
        {"path": "0.0.0.1", "op": "ShuffleReaderExec"},
    ]
    chains = dict_chains(tree)
    # writer is unfusable; join has two children so the proj->join chain
    # stops there; the filter's only child is a reader -> run of 1 -> no
    # chain below the join
    assert [[op["op"] for op in c] for c in chains] == [
        ["ProjectionExec", "JoinExec"]]


def test_chain_fingerprint_stable_and_sensitive():
    proj1, filt1, _ = _chain()
    proj2, filt2, _ = _chain()
    sig = schema_sig(filt1.input.schema)
    assert chain_fingerprint([proj1, filt1], sig) \
        == chain_fingerprint([proj2, filt2], sig), \
        "equal chains must share a fingerprint (shared program cache)"
    filt2.predicate = E.BinOp(">", E.Column("x"), E.Lit(99))
    assert chain_fingerprint([proj1, filt1], sig) \
        != chain_fingerprint([proj2, filt2], sig), \
        "a different predicate must change the fingerprint"


# --------------------------------------------------------------------------
# 2. policy + verdicts
# --------------------------------------------------------------------------

def test_policy_from_config_and_defaults():
    assert CompilePolicy.from_config(None).enabled is True
    cfg = BallistaConfig({
        "ballista.compile.enabled": "false",
        "ballista.compile.min.ops": "3",
        "ballista.compile.operators": "FilterExec, ProjectionExec",
        "ballista.compile.donate": "false",
    })
    p = CompilePolicy.from_config(cfg)
    assert p.enabled is False
    assert p.min_ops == 3
    assert p.operators == {"FilterExec", "ProjectionExec"}
    assert p.donate is False
    assert CompilePolicy(min_ops=0).min_ops == 2, \
        "min_ops clamps to 2 (a fused run needs at least 2 operators)"


def test_verdicts_reject_every_doubt():
    pol = CompilePolicy()
    proj, filt, scan = _chain()
    assert _op_verdict(pol, filt) == (True, None)
    assert _op_verdict(pol, proj) == (True, None)

    host_filt = O.FilterExec(scan, E.BinOp(">", E.Column("x"), E.Lit(10)),
                             host_mode=True)
    ok, why = _op_verdict(pol, host_filt)
    assert not ok and "host-mode" in why

    ok, why = _op_verdict(pol, scan)
    assert not ok and why == STATIC_REASONS["MemoryScanExec"]

    agg = O.HashAggregateExec(
        scan, [(E.Column("y"), "y")],
        [O.AggSpec("sum", E.Column("x"), "s")], "partial")
    assert _op_verdict(pol, agg) == (True, None)
    final = O.HashAggregateExec(
        agg, [(E.Column("y"), "y")],
        [O.AggSpec("sum", E.Column("s"), "s")], "final")
    ok, why = _op_verdict(pol, final)
    assert not ok and "final" in why
    glob = O.HashAggregateExec(
        scan, [], [O.AggSpec("sum", E.Column("x"), "s")], "partial")
    ok, why = _op_verdict(pol, glob)
    assert not ok and "global" in why
    clustered = O.HashAggregateExec(
        scan, [(E.Column("y"), "y")],
        [O.AggSpec("sum", E.Column("x"), "s")], "partial")
    clustered.clustered = (E.Lit(True), [], None)
    ok, why = _op_verdict(pol, clustered)
    assert not ok and "clustered" in why


def test_split_runs_agg_heads_only():
    pol = CompilePolicy()
    scan = _scan()
    filt = O.FilterExec(scan, E.BinOp(">", E.Column("x"), E.Lit(1)))
    agg = O.HashAggregateExec(
        filt, [(E.Column("y"), "y")],
        [O.AggSpec("sum", E.Column("x"), "s")], "partial")
    proj = O.ProjectionExec(agg, [(E.Column("y"), "y"), (E.Column("s"), "s")])
    chain = [("0.0", proj), ("0.0.0", agg), ("0.0.0.0", filt),
             ("0.0.0.0.0", scan)]
    runs, rejected = _split_runs(pol, chain)
    # the aggregate may only HEAD a fused program: proj's run closes, the
    # aggregate opens its own with the filter inside it
    assert [[type(n).__name__ for _p, n in r] for r in runs] == [
        ["ProjectionExec"],
        ["HashAggregateExec", "FilterExec"]]
    assert [r["op"] for r in rejected] == ["MemoryScanExec"]


def test_fused_ctor_validates_linkage():
    proj, filt, _scan_ = _chain()
    with pytest.raises(InternalError):
        FusedStageExec([proj])  # needs >= 2 ops
    other = O.FilterExec(_scan(), E.BinOp(">", E.Column("x"), E.Lit(5)))
    with pytest.raises(InternalError):
        FusedStageExec([proj, other])  # not input-linked


# --------------------------------------------------------------------------
# 3. fused execution == interpreted execution
# --------------------------------------------------------------------------

def test_row_chain_fused_matches_interpreted():
    proj, filt, scan = _chain(n=500, partitions=2)
    ctx = _ctx()
    interpreted = [proj.execute(p, ctx) for p in range(2)]
    proj2, filt2, _ = _chain(n=500, partitions=2)
    fused = FusedStageExec([proj2, filt2])
    got = [fused.execute(p, ctx) for p in range(2)]
    for p in range(2):
        assert _rows(got[p]) == _rows(interpreted[p])
    assert fused.schema.names() == proj.schema.names()


def test_row_chain_donates_columns_and_mask(monkeypatch):
    """ROADMAP #2 via the donation-safety analyzer: the mask (arg 1) is
    provably dead after the fused row call — same freshness proof as the
    columns — so row-only chains donate BOTH buffers.  CPU gates donation
    off, so force the gate and capture what _build hands observed_jit."""
    import jax

    from arrow_ballista_tpu.compile import fused as fused_mod

    captured = {}
    real = fused_mod.observed_jit

    def spy(sig, fn=None, **kw):
        captured[sig] = dict(kw)
        return real(sig, fn, **kw)

    monkeypatch.setattr(fused_mod, "observed_jit", spy)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    proj, filt, _ = _chain(n=100, partitions=1)
    fused = FusedStageExec([proj, filt], donate=True)
    fused._build(_ctx())
    assert captured[fused.fused_sig()]["donate_argnums"] == (0, 1)

    # agg-headed chains donate too since plan-ahead capacity: out_cap is
    # sized before the single jfn call, so there is no retry ladder
    # re-reading donated buffers — inputs are provably dead after call
    scan = _scan(n=100, partitions=1)
    filt_a = O.FilterExec(scan, E.BinOp(">", E.Column("x"), E.Lit(5)))
    agg = O.HashAggregateExec(
        filt_a, [(E.Column("y"), "y")],
        [O.AggSpec("sum", E.Column("x"), "sx")], "partial")
    fused_a = FusedStageExec([agg, filt_a], donate=True)
    captured.clear()
    fused_a._build(_ctx())
    assert captured[fused_a.fused_sig()]["donate_argnums"] == (0, 1)


def test_agg_chain_fused_matches_interpreted():
    ctx = _ctx()

    def build():
        scan = _scan(n=1000, partitions=2)
        filt = O.FilterExec(scan, E.BinOp(">", E.Column("x"), E.Lit(100)))
        agg = O.HashAggregateExec(
            filt, [(E.Column("y"), "y")],
            [O.AggSpec("sum", E.Column("x"), "sx"),
             O.AggSpec("count", E.Column("x"), "n")], "partial")
        return agg, filt

    agg_i, _ = build()
    interpreted = [agg_i.execute(p, ctx) for p in range(2)]
    agg_f, filt_f = build()
    fused = FusedStageExec([agg_f, filt_f])
    got = [fused.execute(p, ctx) for p in range(2)]
    for p in range(2):
        assert _rows(got[p]) == _rows(interpreted[p])


def test_runtime_fallback_latches_to_interpreted():
    # unique literals: a fresh fingerprint so the shared-program cache
    # cannot satisfy this chain (the broken _build below must be reached)
    scan = _scan(n=200, partitions=1)
    filt = O.FilterExec(scan, E.BinOp(">", E.Column("x"), E.Lit(173)))
    proj = O.ProjectionExec(
        filt, [(E.BinOp("*", E.Column("x"), E.Lit(757)), "xx")])
    fused = FusedStageExec([proj, filt])
    ctx = _ctx()
    baseline = _rows(proj.execute(0, ctx))

    def boom(ctx_):
        raise RuntimeError("injected kernel-build failure")

    fused._build = boom  # first fused attempt dies inside the safety valve
    got = _rows(fused.execute(0, ctx))
    assert got == baseline, "fallback must produce the interpreted answer"
    assert fused._fallback, "the interpreted path must be latched"
    assert fused.metrics().to_dict().get("fused_fallbacks") == 1


def test_metrics_deferred_resolver_may_reenter_add():
    # Regression: the fused aggregate's deferred output_rows resolver
    # records fused_passthrough_fallbacks on the SAME metrics set when the
    # poor-reduction probe fires.  to_dict resolves deferred fns under the
    # lock, so add must be reentrant — a plain Lock deadlocked q20 at SF1
    # (the only query whose partial agg is big and poor enough to latch).
    import threading

    m = MetricsSet()

    def resolver():
        m.add("reentrant_latch", 1)
        return 7

    m.add_deferred("output_rows", resolver)
    got = {}
    t = threading.Thread(target=lambda: got.update(m.to_dict()))
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "to_dict deadlocked on a deferred re-entrant add"
    assert got["output_rows"] == 7
    assert got["reentrant_latch"] == 1


# --------------------------------------------------------------------------
# 4. serde
# --------------------------------------------------------------------------

def test_fused_plan_serde_roundtrip():
    proj, filt, scan = _chain()
    fused = FusedStageExec([proj, filt], donate=True)
    obj = json.loads(json.dumps(serde.plan_to_obj(fused)))
    back = serde.plan_from_obj(obj)
    assert isinstance(back, FusedStageExec)
    assert back.donate is True
    assert [type(o).__name__ for o in back.ops] == \
        ["ProjectionExec", "FilterExec"]
    assert back.ops[0].input is back.ops[1], "chain links must survive"
    assert type(back.input).__name__ == "MemoryScanExec"


def test_graph_checkpoint_carries_fusion_records():
    graph = _graph()
    fused_stages = [s for s in graph.stages.values()
                    if s.resolved_plan is not None
                    and _fused_nodes(s.resolved_plan)]
    assert fused_stages, "the leaf group-by stage must fuse"
    assert graph.compile_log, "fusion decisions must land in compile_log"
    obj = json.loads(json.dumps(serde.graph_to_obj(graph)))
    back = serde.graph_from_obj(obj)
    assert [r["kind"] for r in back.compile_log] \
        == [r["kind"] for r in graph.compile_log]
    for sid, stage in graph.stages.items():
        assert [r.get("fused") for r in back.stages[sid].fusion_rewrites] \
            == [r.get("fused") for r in stage.fusion_rewrites]
    # recovered graphs have no policy installed: conservative default
    assert back.compiler is None


# --------------------------------------------------------------------------
# 5. scheduler integration + interplay
# --------------------------------------------------------------------------

def test_leaf_stage_fuses_and_disabled_policy_does_not():
    on = _graph(enabled=True)
    assert any(_fused_nodes(s.resolved_plan) for s in on.stages.values()
               if s.resolved_plan is not None)
    off = _graph(enabled=False)
    assert not any(_fused_nodes(s.resolved_plan)
                   for s in off.stages.values()
                   if s.resolved_plan is not None)
    assert not off.compile_log


def test_fuse_stage_idempotent_per_attempt():
    graph = _graph()
    stage = next(s for s in graph.stages.values()
                 if s.resolved_plan is not None
                 and _fused_nodes(s.resolved_plan))
    before = len(stage.fusion_rewrites)
    assert fuse_stage(graph, stage) == 0, \
        "same attempt must not re-fuse (or re-record)"
    assert len(stage.fusion_rewrites) == before
    assert len(_fused_nodes(stage.resolved_plan)) == 1


def test_task_ships_fused_plan_and_speculative_duplicate_shares_it():
    graph = _graph()
    stage = next(s for s in graph.stages.values()
                 if s.resolved_plan is not None
                 and _fused_nodes(s.resolved_plan))
    t = graph.pop_next_task("exec-0")
    assert t is not None and t.task.stage_id == stage.stage_id
    assert _fused_nodes(t.plan), "launched tasks must carry the fused plan"
    # a speculative duplicate launches from the same resolved plan object,
    # so it executes the SAME fused kernel as the primary
    spec = graph.launch_speculative(stage.stage_id, t.task.partition,
                                    "exec-1")
    assert spec is not None
    assert spec.task.speculative
    assert _fused_nodes(spec.plan), \
        "the duplicate attempt must run the fused kernel too"
    assert spec.plan is t.plan


def test_rollback_re_resolves_and_keeps_single_fusion():
    graph = _graph()
    stage = next(s for s in graph.stages.values()
                 if s.fusion_rewrites
                 and any(r["fused"] for r in s.fusion_rewrites))
    attempt = stage.stage_attempt
    stage.rollback()
    assert stage.resolved_plan is None
    graph.revive()
    assert stage.stage_attempt == attempt + 1
    assert stage.resolved_plan is not None
    # the re-resolved attempt re-decided fusion under the new epoch and
    # never double-wrapped: exactly one fused node in the live plan
    assert stage._fused_attempt == stage.stage_attempt, \
        "revive must re-run the fusion decision for the new attempt"
    assert len(_fused_nodes(stage.resolved_plan)) == 1
    drain(graph)
    assert graph.status == "successful"


def test_aqe_coalesce_validates_against_fused_producer():
    """AQE's dynamic coalescing rewrites the CONSUMER of the fused
    stage's output; both rewrites must coexist on one graph and the job
    must still complete (validate_rewrite re-checks the mutated stage)."""
    from arrow_ballista_tpu.scheduler.aqe import AqePolicy

    graph = _graph(partitions=8)
    graph.aqe = AqePolicy(enabled=True)
    drain(graph)
    assert graph.status == "successful"
    assert any(r["fused"] for r in graph.compile_log)


# --------------------------------------------------------------------------
# 6. e2e (standalone) + observability convergence
# --------------------------------------------------------------------------

def _frame(rng, n=2000, groups=9):
    return pd.DataFrame({
        "g": rng.integers(0, groups, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })


def _run_standalone(sql, df, enabled, tables=("t",)):
    cfg = BallistaConfig({
        "ballista.shuffle.partitions": "4",
        "ballista.compile.enabled": str(enabled).lower(),
        # tiny test data: don't let the advisor's savings floor hide chains
        "ballista.observability.device.advisor.min_savings_ms": "0",
    })
    c = BallistaContext.standalone(cfg)
    try:
        for name in tables:
            c.register_table(name, df)
        out = c.sql(sql).to_pandas()
        logs = []
        jobs = c._standalone.scheduler.jobs
        for jid in list(getattr(jobs, "_graphs", {}) or {}):
            logs.extend(getattr(jobs.get_graph(jid), "compile_log", []))
        return out, logs, c
    except BaseException:
        c.shutdown()
        raise


def test_standalone_fusion_ab_identical_and_observable():
    rng = np.random.default_rng(42)
    df = _frame(rng)
    sql = ("select g, sum(v) as s, count(*) as n from t "
           "where v > 10 group by g order by g")
    on, logs_on, c_on = _run_standalone(sql, df, True)
    try:
        fused_recs = [r for r in logs_on if r["fused"]]
        assert fused_recs, "the partial-agg stage must fuse"
        assert any("HashAggregateExec" in run
                   for r in fused_recs for run in r["fused_ops"]), \
            "the fused run must include the partial aggregate"
        rep = c_on.explain_analyze(sql)
        assert "FusedStageExec" in rep["text"], \
            "EXPLAIN ANALYZE must show the fused kernel"
        assert any("fused " in _hdr for _hdr in rep["text"].splitlines()), \
            "the stage header must carry the fusion annotation"
        # advisor convergence: the fused chain is marked fused=True
        adv = c_on.advise(sql)
        assert any(cand["fused"] for cand in adv["candidates"])
        assert "[FUSED]" in adv["text"]
    finally:
        c_on.shutdown()
    off, logs_off, c_off = _run_standalone(sql, df, False)
    c_off.shutdown()
    assert not logs_off
    # bit-identical: fused output must equal the interpreted output
    pd.testing.assert_frame_equal(on, off)


def test_advisor_reports_rejection_reason():
    rng = np.random.default_rng(3)
    # float64 arithmetic plans host-mode operators: allowlist rejects
    df = pd.DataFrame({
        "g": rng.integers(0, 5, 800).astype(np.int64),
        "v": rng.normal(size=800),
    })
    sql = ("select g, sum(v) as s from t where v > 0.1 "
           "group by g order by g")
    out, logs, c = _run_standalone(sql, df, True)
    try:
        adv = c.advise(sql)
        rejected = [cand for cand in adv["candidates"]
                    if not cand["fused"] and cand["reason"]]
        assert rejected, "rejected chains must carry a reason"
    finally:
        c.shutdown()


def test_doctor_fusion_missed_threshold():
    from arrow_ballista_tpu.obs.doctor import (
        FUSION_MISSED_MIN_SAVINGS_MS,
        diagnose,
    )

    def bundle(retraces, compile_s):
        stage = {
            "stage_id": 1, "state": "successful", "stage_attempt": 0,
            "partitions": 2, "planned_partitions": 2, "tasks_completed": 2,
            "task_launches": 2, "speculative_launches": 0,
            "output_rows": 10, "output_bytes": 100,
            "partition_rows": {}, "partition_bytes": {}, "skew": 1.0,
            "row_histogram": {"edges": [], "counts": []},
            "task_duration_s": {"count": 2, "p50": 0.1, "p75": 0.1,
                                "p95": 0.1, "max": 0.1, "mean": 0.1},
            "operators": {
                "0.0:HashAggregateExec": {"output_rows": 10},
                "0.0.0:FilterExec": {
                    "jit_compile_time": compile_s,
                    "jit_compiles": 1, "jit_retraces": retraces,
                },
            },
            "device": {}, "aqe": [],
            "fusion": [{
                "kind": "fusion", "stage_id": 1, "stage_attempt": 0,
                "operators": ["HashAggregateExec", "FilterExec"],
                "paths": ["0.0", "0.0.0"],
                "fused": False, "fused_ops": [],
                "rejected": [{"op": "HashAggregateExec", "path": "0.0",
                              "reason": "aggregate mode 'final'"}],
                "donate": False,
            }],
        }
        return {"schema": "ballista.forensics/v1", "job_id": "j",
                "generated_ts_ms": 0, "status": {"state": "successful"},
                "journal": [], "stages": [stage], "aqe_log": [],
                "metrics": {}, "cluster_history": {}}

    # pure first-compile cost never fires the rule (a fused kernel
    # compiles once too)
    cold = diagnose(bundle(retraces=0, compile_s=1.0))
    assert "fusion-missed" not in [f["rule"] for f in cold["findings"]]
    # heavy RETRACE share above the threshold does
    hot = diagnose(bundle(retraces=9, compile_s=1.0))
    missed = [f for f in hot["findings"] if f["rule"] == "fusion-missed"]
    assert missed, "retrace-dominated rejected chain must be diagnosed"
    f = missed[0]
    assert f["evidence"]["est_savings_ms"] >= FUSION_MISSED_MIN_SAVINGS_MS
    assert any("final" in r for r in f["evidence"]["rejected"])
    assert "ballista.compile" in f["remedy"]
    assert "fusion-missed" in hot["rules_evaluated"]


def test_repeated_template_reports_zero_new_compiles():
    """Plan-cache repeat contract: the second run of the same statement
    reuses the shared fused program — 0 new jit compiles."""
    rng = np.random.default_rng(11)
    df = _frame(rng)
    sql = ("select g, sum(v) as s from t where v > 25 "
           "group by g order by g")
    cfg = BallistaConfig({
        "ballista.shuffle.partitions": "2",
        "ballista.compile.enabled": "true",
    })
    c = BallistaContext.standalone(cfg)
    try:
        c.register_table("t", df)
        first = c.sql(sql).to_pandas()
        rep1 = c.explain_analyze(sql)
        again = c.sql(sql).to_pandas()
        pd.testing.assert_frame_equal(first, again)
        # sum fused-kernel compiles across the LAST run's stages: the
        # shared_program cache means the fused signature never recompiles
        last = c.explain_analyze(sql)
        fused_ops = [op
                     for st in last["stages"]
                     for op in st["operator_tree"]
                     if op["op"] == "FusedStageExec"]
        assert fused_ops, "repeat run must still show the fused kernel"
        assert sum(op["compiles"] for op in fused_ops) == 0, \
            "a repeated statement must report 0 new fused compiles"
    finally:
        c.shutdown()


# --------------------------------------------------------------------------
# 7. chaos: executor killed mid-fused-task
# --------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_executor_killed_mid_fused_task(tmp_path):
    """Fault-recovery interplay: kill an executor right before it runs a
    task whose stage plan carries a FusedStageExec.  The scheduler's
    lineage machinery must re-run the work and the final answer must
    equal the fusion-OFF oracle — the fused kernel adds no new failure
    mode."""
    from arrow_ballista_tpu import faults

    from .test_chaos import (
        SQL,
        _client,
        _frames_equal,
        _make_cluster,
        _teardown,
    )

    sched, executors = _make_cluster(tmp_path)
    try:
        c_off = _client(sched.port)
        c_off.config.set("ballista.compile.enabled", "false")
        oracle = c_off.sql(SQL).to_pandas()
        c_off.shutdown()

        c = _client(sched.port)  # compiler on by default
        victim = executors[1]
        plan = faults.FaultPlan.from_obj({"seed": 7, "rules": [{
            "site": "executor.task.before_run", "action": "kill",
            "match": {"executor_id": victim.metadata.executor_id},
            "on_hit": 1, "times": 1}]})
        with faults.use_plan(plan):
            got = c.sql(SQL).to_pandas()

        assert victim._killed, "the kill must reach the registered target"
        _frames_equal(got, oracle)
        # the surviving run really did fuse: some graph on the scheduler
        # recorded an installed kernel
        jobs = sched.server.jobs
        logs = []
        for jid in list(getattr(jobs, "_graphs", {}) or {}):
            logs.extend(getattr(jobs.get_graph(jid), "compile_log", []))
        assert any(r.get("fused") for r in logs), \
            "the killed run's stages must have carried fused kernels"
        c.shutdown()
    finally:
        _teardown(sched, executors)


# --------------------------------------------------------------------------
# 8. SF1 oracle sweep (slow: needs the generated TPC-H dataset)
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_sf1_all_queries_bit_identical_fusion_on_vs_off():
    """The whole TPC-H suite at SF1, fusion on vs off, through the
    standalone scheduler: every query's result frame must be EXACTLY
    equal — the compiler is a pure performance rewrite."""
    import os

    from benchmarks.queries import QUERIES
    from benchmarks.tpch import register_tables

    data = os.path.join(os.path.dirname(__file__), "..",
                        ".bench_data", "tpch-sf1")
    if not os.path.exists(os.path.join(data, "lineitem.parquet")):
        pytest.skip("TPC-H SF1 dataset not generated "
                    "(python -m benchmarks.tpch convert --scale 1 "
                    "--output .bench_data/tpch-sf1)")

    def run(enabled):
        cfg = BallistaConfig({
            "ballista.shuffle.partitions": "4",
            "ballista.compile.enabled": str(enabled).lower(),
        })
        c = BallistaContext.standalone(cfg, concurrent_tasks=4)
        out, logs = {}, []
        try:
            register_tables(c, data)
            for q in sorted(QUERIES):
                out[q] = c.sql(QUERIES[q]).to_pandas()
            jobs = c._standalone.scheduler.jobs
            for jid in list(getattr(jobs, "_graphs", {}) or {}):
                logs.extend(getattr(jobs.get_graph(jid), "compile_log", []))
        finally:
            c.shutdown()
        return out, logs

    on, logs_on = run(True)
    off, logs_off = run(False)
    assert not logs_off
    assert any(r.get("fused") for r in logs_on), \
        "the fusion-on sweep must have installed at least one kernel"
    mismatched = []
    for q in sorted(on):
        try:
            pd.testing.assert_frame_equal(on[q], off[q])
        except AssertionError as exc:
            mismatched.append((q, str(exc).splitlines()[0]))
    assert not mismatched, \
        f"{len(mismatched)}/22 queries differ fusion-on vs off: {mismatched}"
