"""Log <-> trace correlation (utils/logsetup.py).

The executor's task wrapper and the scheduler's event dispatch enter
``log_scope(job_id=..., ...)``; ``ContextFilter`` stamps the ambient ids
onto every record, the text format appends a ``[job=...]`` suffix and
``ballista.log.format=json`` switches to one-JSON-object-per-line output
— so ``grep job-42`` over daemon logs lines up with the flight-recorder
timeline and the span store (see docs/user-guide/doctor.md).
"""
import io
import json
import logging

import pytest

from arrow_ballista_tpu.utils.logsetup import (
    ContextFilter,
    JsonFormatter,
    TextFormatter,
    _FORMAT,
    _make_formatter,
    init_logging,
    log_scope,
)


def _capture_logger(formatter):
    """A throwaway logger wired like init_logging wires the root."""
    buf = io.StringIO()
    h = logging.StreamHandler(buf)
    h.setFormatter(formatter)
    h.addFilter(ContextFilter())
    logger = logging.getLogger(f"corr-{id(buf)}")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    logger.addHandler(h)
    return logger, buf


def test_filter_stamps_ambient_scope_and_restores_on_exit():
    f = ContextFilter()

    def record():
        r = logging.LogRecord("n", logging.INFO, "p", 1, "m", (), None)
        f.filter(r)
        return r

    # outside any scope the attributes exist (formatters rely on that)
    # but are empty
    r = record()
    assert (r.job_id, r.trace_id, r.span_id) == ("", "", "")
    with log_scope(job_id="job-42", trace_id="t" * 32, span_id="s" * 16):
        r = record()
        assert r.job_id == "job-42"
        assert r.trace_id == "t" * 32
        assert r.span_id == "s" * 16
        with log_scope(job_id="job-43"):  # nested scope wins...
            assert record().job_id == "job-43"
        assert record().job_id == "job-42"  # ...and the outer is restored
    assert record().job_id == ""


def test_text_format_appends_job_suffix_only_inside_scope():
    logger, buf = _capture_logger(TextFormatter(_FORMAT))
    logger.info("plain")
    with log_scope(job_id="job-7", trace_id="abc123"):
        logger.info("scoped")
    plain, scoped = buf.getvalue().strip().splitlines()
    assert "plain" in plain and "[job=" not in plain
    assert scoped.endswith("[job=job-7 trace=abc123]")


def test_json_format_one_object_per_line_with_correlation_fields():
    logger, buf = _capture_logger(JsonFormatter())
    logger.info("hello %s", "world")
    with log_scope(job_id="job-9", trace_id="t" * 32, span_id="s" * 16):
        try:
            raise ValueError("boom")
        except ValueError:
            logger.exception("task failed")
    lines = [json.loads(ln) for ln in buf.getvalue().strip().splitlines()]
    assert len(lines) == 2
    plain, scoped = lines
    assert plain["message"] == "hello world"
    assert plain["level"] == "INFO"
    # fields are omitted (not empty-valued) outside a scope: aggregators
    # index what exists
    assert "job_id" not in plain and "trace_id" not in plain
    assert scoped["job_id"] == "job-9"
    assert scoped["trace_id"] == "t" * 32
    assert scoped["span_id"] == "s" * 16
    assert "ValueError: boom" in scoped["exc"]


def test_make_formatter_selects_and_rejects():
    assert isinstance(_make_formatter("json"), JsonFormatter)
    assert isinstance(_make_formatter("text"), TextFormatter)
    with pytest.raises(ValueError, match="unknown log format"):
        _make_formatter("yaml")


def test_init_logging_reads_env_when_fmt_unset(monkeypatch):
    root = logging.getLogger()
    saved = list(root.handlers)
    try:
        monkeypatch.setenv("BALLISTA_LOG_FORMAT", "json")
        init_logging("INFO")
        assert isinstance(root.handlers[0].formatter, JsonFormatter)
        # explicit fmt beats env (daemons pass --log-format through)
        init_logging("INFO", fmt="text")
        assert isinstance(root.handlers[0].formatter, TextFormatter)
        for h in root.handlers:
            assert any(isinstance(f, ContextFilter) for f in h.filters)
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
        for h in saved:
            root.addHandler(h)


def test_ambient_scope_is_entered_by_task_and_dispatch_paths():
    """The correlation contract: the executor's task wrapper and the
    scheduler's per-job event dispatch actually enter log_scope, so job
    logs correlate without per-call plumbing."""
    import inspect

    from arrow_ballista_tpu.executor import executor as executor_mod
    from arrow_ballista_tpu.scheduler import scheduler as scheduler_mod

    assert "log_scope(" in inspect.getsource(executor_mod)
    assert "log_scope(" in inspect.getsource(scheduler_mod)
