"""Adaptive query execution tests (scheduler/aqe.py).

Drives the three runtime rewrites — dynamic partition coalescing,
shuffle-join -> broadcast switch (with probe-exchange grafting), and skew
splitting — through the real ExecutionGraph with fabricated task
completions (the test_scheduler.py virtual-cluster seam), then checks the
systems invariants ISSUE 7 calls out: rollback restores planned
partitioning, checkpoints persist the MUTATED graph, the failpoint window
degrades AQE to a no-op, and ``ballista.aqe.enabled=false`` reproduces the
static plans.
"""
import itertools
from typing import Dict

import numpy as np
import pyarrow as pa

from arrow_ballista_tpu import faults
from arrow_ballista_tpu.catalog import MemoryTable, SchemaCatalog
from arrow_ballista_tpu.ops.operators import JoinExec
from arrow_ballista_tpu.ops.shuffle import (
    ShuffleReaderExec,
    ShuffleWritePartition,
    UnresolvedShuffleExec,
)
from arrow_ballista_tpu.scheduler.aqe import (
    FAILPOINT,
    AqePolicy,
    _split_indices,
)
from arrow_ballista_tpu.scheduler.execution_graph import (
    RUNNING,
    SUCCESSFUL,
    UNRESOLVED,
    ExecutionGraph,
)
from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner
from arrow_ballista_tpu.scheduler.planner import collect_nodes
from arrow_ballista_tpu.scheduler.types import (
    FETCH_PARTITION_ERROR,
    FailedReason,
    TaskStatus,
)
from arrow_ballista_tpu.serde import graph_from_obj, graph_to_obj
from arrow_ballista_tpu.sql.optimizer import optimize
from arrow_ballista_tpu.sql.parser import parse_sql
from arrow_ballista_tpu.sql.planner import SqlToRel
from arrow_ballista_tpu.utils.config import BallistaConfig

from .test_scheduler import drain, fake_success, physical_plan


def join_plan(partitions: int = 4):
    """Two-table inner join planned as a PARTITIONED join (the static
    broadcast threshold is zeroed so only the runtime switch can fire)."""
    rng = np.random.default_rng(0)
    big = pa.table({
        "k": pa.array(rng.integers(0, 50, 2000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 2000).astype(np.int64)),
    })
    small = pa.table({
        "k": pa.array(np.arange(50, dtype=np.int64)),
        "w": pa.array(rng.integers(0, 10, 50).astype(np.int64)),
    })
    catalog = SchemaCatalog()
    catalog.register(MemoryTable("big", big))
    catalog.register(MemoryTable("small", small))
    config = BallistaConfig({
        "ballista.shuffle.partitions": str(partitions),
        "ballista.join.broadcast_threshold": "0",
    })
    sql = "select big.k, big.v, small.w from big join small on big.k = small.k"
    logical = optimize(SqlToRel(catalog).plan(parse_sql(sql)))
    return PhysicalPlanner(catalog, config).plan_query(logical).plan


def sized_success(rows_per_bucket: Dict[int, int], bytes_per_row: int = 10):
    """Outcome hook fabricating shuffle writes with controlled sizes."""

    def hook(task):
        writer = task.plan
        if writer.partitioning is None:
            return None
        writes = [
            ShuffleWritePartition(
                q, f"/fake/{task.task.job_id}/{task.task.stage_id}"
                   f"/{task.task.partition}/data-{q}.arrow",
                rows_per_bucket.get(q, 10),
                rows_per_bucket.get(q, 10) * bytes_per_row)
            for q in range(writer.partitioning.count)
        ]
        return TaskStatus(task.task, "exec-0", "success",
                          shuffle_writes=writes)

    return hook


def pump_until(graph, cond, hooks=None, executor="exec-0"):
    """Complete popped tasks (per-stage hooks) until ``cond()`` holds."""
    hooks = hooks or {}
    events = []
    for _ in range(10000):
        if cond():
            return events
        t = graph.pop_next_task(executor)
        if t is None:
            raise AssertionError(f"graph stalled before condition: {graph!r}")
        hook = hooks.get(t.task.stage_id)
        st = hook(t) if hook else None
        events.extend(graph.update_task_status(
            [st or fake_success(t, executor)]))
    raise AssertionError("condition never reached")


# --------------------------------------------------------------------------
# slicing helper
# --------------------------------------------------------------------------

def test_split_indices_balanced():
    assert _split_indices([10, 10, 10, 10], 2) == [(0, 2), (2, 4)]
    # heavily skewed weights still produce k contiguous non-empty slices
    slices = _split_indices([100, 1, 1, 1], 3)
    assert len(slices) == 3
    assert slices[0][0] == 0 and slices[-1][1] == 4
    for (a, b), (c, _d) in zip(slices, slices[1:]):
        assert b == c and a < b
    # k > n clamps to one element per slice
    assert _split_indices([5, 5], 8) == [(0, 1), (1, 2)]


# --------------------------------------------------------------------------
# dynamic partition coalescing
# --------------------------------------------------------------------------

def test_dynamic_coalesce_groups_tiny_partitions():
    graph = ExecutionGraph.build("j", physical_plan(partitions=8))
    graph.aqe = AqePolicy(coalesce_target_rows=1700, coalesce_target_bytes=0,
                          skew_enabled=False, broadcast_enabled=False)
    # stage 1's 8 map tasks write 100 rows each into all 8 hash buckets
    # (800 rows per reduce partition): with a 1700-row target, adjacent
    # pairs merge -> 4 tasks instead of 8
    pump_until(graph, lambda: graph.stages[2].state == RUNNING,
               hooks={1: sized_success({q: 100 for q in range(8)},
                                       bytes_per_row=1)})
    stage2 = graph.stages[2]
    assert stage2.partitions == 4
    assert stage2.planned_partitions == 8
    assert len(stage2.task_infos) == 4
    readers = collect_nodes(stage2.resolved_plan, ShuffleReaderExec)
    for r in readers:
        assert r.partition_count == 4
        assert sorted(r.locations) == [0, 1, 2, 3]
        assert r._orig_partition_count == 8
        # each merged task reads exactly two source partitions' outputs
        assert all(sum(l.num_rows for l in locs) == 1600
                   for locs in r.locations.values())
    [rec] = stage2.aqe_rewrites
    assert rec["kinds"] == ["coalesce"]
    assert rec["partitions_before"] == 8 and rec["partitions_after"] == 4
    assert rec["coalesced_partitions"] == 4
    assert graph.aqe_log == [rec]
    assert ("coalesce", 4) in graph.aqe_events
    drain(graph)
    assert graph.status == "successful"


def test_coalesce_rollback_restores_planned_partitions():
    graph = ExecutionGraph.build("j", physical_plan(partitions=8))
    graph.aqe = AqePolicy(coalesce_target_rows=1700, coalesce_target_bytes=0,
                          skew_enabled=False, broadcast_enabled=False)
    pump_until(graph, lambda: graph.stages[2].state == RUNNING,
               hooks={1: sized_success({q: 100 for q in range(8)},
                                       bytes_per_row=1)})
    stage2 = graph.stages[2]
    assert stage2.partitions == 4
    # a fetch failure rolls stage 2 back: the planned 8-way layout must
    # come back (the re-resolve re-decides from the NEW attempt's sizes)
    t = graph.pop_next_task("exec-0")
    assert t.task.stage_id == 2
    graph.update_task_status([TaskStatus(
        t.task, "exec-0", "failed",
        failure=FailedReason(FETCH_PARTITION_ERROR, "dead peer",
                             map_stage_id=1, map_partition_id=0,
                             executor_id="exec-0"))])
    assert stage2.state == UNRESOLVED
    assert stage2.partitions == 8
    assert getattr(stage2, "_orig_partitions", None) is None
    # producer re-runs, consumer re-resolves, AQE re-applies, job finishes
    drain(graph)
    assert graph.status == "successful"
    # re-decided from the re-run attempt's real sizes: only map task 0
    # re-ran (with tiny default fake writes), so adjacent buckets still
    # merge pairwise
    assert stage2.partitions == 4
    assert len(stage2.aqe_rewrites) == 2  # one record per resolve epoch


def test_aqe_disabled_uses_static_path():
    graph = ExecutionGraph.build("j", physical_plan(partitions=8))
    graph.aqe = AqePolicy(enabled=False)
    pump_until(graph, lambda: graph.stages[2].state == RUNNING,
               hooks={1: sized_success({q: 100 for q in range(8)},
                                       bytes_per_row=1)})
    stage2 = graph.stages[2]
    # static heuristic: 800 rows <= 8192 collapses all the way to ONE task
    assert stage2.partitions == 1
    assert stage2.aqe_rewrites == [] and graph.aqe_log == []
    drain(graph)
    assert graph.status == "successful"


def test_aqe_defaults_subsume_static_collapse():
    """With default targets the dynamic pass makes the same call the
    static heuristic made for q1-style tiny finals: collapse to one."""
    graph_dyn = ExecutionGraph.build("j1", physical_plan(partitions=8))
    graph_sta = ExecutionGraph.build("j2", physical_plan(partitions=8))
    graph_sta.aqe = AqePolicy(enabled=False)
    for g in (graph_dyn, graph_sta):
        drain(g)  # default fake writes: 10 rows per bucket
        assert g.status == "successful"
    assert graph_dyn.stages[2].partitions == 1
    assert graph_sta.stages[2].partitions == 1


# --------------------------------------------------------------------------
# skew splitting
# --------------------------------------------------------------------------

def _hot_bucket_hook(hot_rows: int, files_per_bucket: int = 2):
    """Every map task writes ``files_per_bucket`` files into bucket 0
    (``hot_rows`` rows each) and tiny files into the rest — a splittable
    hot partition."""

    def hook(task):
        writer = task.plan
        if writer.partitioning is None:
            return None
        writes = []
        for q in range(writer.partitioning.count):
            for i in range(files_per_bucket if q == 0 else 1):
                rows = hot_rows if q == 0 else 10
                writes.append(ShuffleWritePartition(
                    q, f"/fake/{task.task.job_id}/{task.task.stage_id}"
                       f"/{task.task.partition}/data-{q}-{i}.arrow",
                    rows, rows * 10))
        return TaskStatus(task.task, "exec-0", "success",
                          shuffle_writes=writes)

    return hook


def test_skew_split_hot_partition():
    graph = ExecutionGraph.build("j", join_plan(partitions=4))
    graph.aqe = AqePolicy(coalesce_enabled=False, broadcast_enabled=False,
                          skew_factor=2.0, skew_min_rows=1000)
    consumer = next(s for s in graph.stages.values()
                    if collect_nodes(s.plan, JoinExec))
    join = collect_nodes(consumer.plan, JoinExec)[0]
    probe_sid, build_sid = join.left.stage_id, join.right.stage_id
    # probe exchange: 2 files x 2000 rows land in bucket 0 per map task
    pump_until(graph, lambda: consumer.state == RUNNING,
               hooks={probe_sid: _hot_bucket_hook(2000)})
    assert consumer.partitions > 4, "hot partition must split into tasks"
    [rec] = consumer.aqe_rewrites
    assert rec["kinds"] == ["skew"]
    n_split = rec["skew_splits"][0]["tasks"]
    assert rec["skew_splits"] == [{"partition": 0, "tasks": n_split}]
    assert consumer.partitions == n_split + 3
    readers = collect_nodes(consumer.resolved_plan, ShuffleReaderExec)
    probe_r = next(r for r in readers if r.stage_id == probe_sid)
    build_r = next(r for r in readers if r.stage_id == build_sid)
    split_tasks = [g for g in range(consumer.partitions)
                   if any(l.num_rows == 2000 for l in probe_r.locations[g])]
    assert len(split_tasks) == n_split
    # the split target reads a SLICE per task; the union covers every
    # hot-bucket file exactly once
    total_hot_files = sum(
        1 for q, (_ex, writes) in graph.stages[probe_sid].outputs.items()
        for w in writes if w.output_partition == 0)
    assert sum(len(probe_r.locations[g]) for g in split_tasks) \
        == total_hot_files
    # the build side replicates bucket 0 IN FULL into every slice task
    for g in split_tasks:
        assert [l.path for l in build_r.locations[g]] \
            == [l.path for l in build_r.locations[split_tasks[0]]]
    assert ("skew", 1) in graph.aqe_events
    drain(graph)
    assert graph.status == "successful"


def test_no_skew_split_when_unsafe():
    """The final-aggregate stage of a group-by must NOT split a hot
    partition: a final HashAggregate dedups across the whole partition."""
    graph = ExecutionGraph.build("j", physical_plan(partitions=4))
    graph.aqe = AqePolicy(coalesce_enabled=False, broadcast_enabled=False,
                          skew_factor=1.5, skew_min_rows=100)
    pump_until(graph, lambda: graph.stages[2].state == RUNNING,
               hooks={1: _hot_bucket_hook(5000)})
    stage2 = graph.stages[2]
    assert stage2.partitions == 4, "final agg stage must stay unsplit"
    assert stage2.aqe_rewrites == []


# --------------------------------------------------------------------------
# broadcast switch + probe-exchange graft
# --------------------------------------------------------------------------

def hold_probe_finish_build(graph, probe_sid, build_sid):
    """Finish the build exchange while the probe exchange's tasks stay in
    flight (popped, never reported).  Returns (held tasks, events)."""
    held, events = [], []
    for _ in range(100):
        if graph.stages[build_sid].state == SUCCESSFUL:
            return held, events
        t = graph.pop_next_task("exec-0")
        assert t is not None, "stalled before build stage completed"
        if t.task.stage_id == probe_sid:
            held.append(t)
            continue
        events.extend(graph.update_task_status([fake_success(t, "exec-0")]))
    raise AssertionError("build stage never completed")


def test_broadcast_switch_grafts_probe_exchange():
    graph = ExecutionGraph.build("j", join_plan(partitions=4))
    graph.aqe = AqePolicy(coalesce_enabled=False, skew_enabled=False,
                          broadcast_threshold_rows=1000)
    consumer = next(s for s in graph.stages.values()
                    if collect_nodes(s.plan, JoinExec))
    join = collect_nodes(consumer.plan, JoinExec)[0]
    probe_sid, build_sid = join.left.stage_id, join.right.stage_id
    n_stages = len(graph.stages)

    held, _events = hold_probe_finish_build(graph, probe_sid, build_sid)
    assert held, "probe tasks must have been in flight"
    assert join.dist == "broadcast"
    assert probe_sid not in graph.stages, "probe exchange must be grafted"
    assert len(graph.stages) == n_stages - 1
    assert consumer.producer_ids == [build_sid]
    [rec] = consumer.aqe_rewrites
    assert rec["kinds"] == ["broadcast"]
    assert rec["build_stage_id"] == build_sid
    assert rec["grafted_stage_id"] == probe_sid
    assert ("broadcast", 1) in graph.aqe_events
    drain(graph)
    assert graph.status == "successful"


def test_broadcast_switch_cancels_inflight_probe_tasks():
    graph = ExecutionGraph.build("j", join_plan(partitions=4))
    graph.aqe = AqePolicy(coalesce_enabled=False, skew_enabled=False,
                          broadcast_threshold_rows=1000)
    consumer = next(s for s in graph.stages.values()
                    if collect_nodes(s.plan, JoinExec))
    join = collect_nodes(consumer.plan, JoinExec)[0]
    probe_sid, build_sid = join.left.stage_id, join.right.stage_id

    held, events = hold_probe_finish_build(graph, probe_sid, build_sid)
    cancels = [payload for kind, payload in events if kind == "cancel_task"]
    assert len(cancels) == len(held) > 0
    for _eid, tid in cancels:
        assert tid.stage_id == probe_sid
    drain(graph)
    assert graph.status == "successful"


def test_broadcast_switch_keeps_completed_probe_exchange():
    """When the probe exchange already finished, the switch still flips
    the join but must NOT throw away completed work."""
    graph = ExecutionGraph.build("j", join_plan(partitions=4))
    graph.aqe = AqePolicy(coalesce_enabled=False, skew_enabled=False,
                          broadcast_threshold_rows=1000)
    consumer = next(s for s in graph.stages.values()
                    if collect_nodes(s.plan, JoinExec))
    join = collect_nodes(consumer.plan, JoinExec)[0]
    probe_sid = join.left.stage_id
    pump_until(graph, lambda: consumer.state != UNRESOLVED)
    assert join.dist == "broadcast"
    assert probe_sid in graph.stages, "completed exchange must be kept"
    recs = [r for r in consumer.aqe_rewrites if r["kinds"] == ["broadcast"]]
    if recs:  # probe done before build: no graft possible
        assert recs[0]["grafted_stage_id"] is None
    drain(graph)
    assert graph.status == "successful"


def test_broadcast_switch_respects_threshold():
    graph = ExecutionGraph.build("j", join_plan(partitions=4))
    graph.aqe = AqePolicy(coalesce_enabled=False, skew_enabled=False,
                          broadcast_threshold_rows=5)  # build writes more
    consumer = next(s for s in graph.stages.values()
                    if collect_nodes(s.plan, JoinExec))
    join = collect_nodes(consumer.plan, JoinExec)[0]
    drain(graph)
    assert graph.status == "successful"
    assert join.dist == "partitioned"
    assert consumer.aqe_rewrites == []


def three_join_plan(partitions: int = 4):
    """q9-shaped chain: (li ⋈ part) ⋈ supp, then aggregate + sort.  The
    middle join's output exchange is a NON-LEAF stage — the probe side of
    the final join reads it through two further producer stages."""
    rng = np.random.default_rng(23)
    n = 2000
    catalog = SchemaCatalog()
    catalog.register(MemoryTable("li", pa.table({
        "pk": pa.array(rng.integers(0, 200, n).astype(np.int64)),
        "sk": pa.array(rng.integers(0, 50, n).astype(np.int64)),
        "qty": pa.array(rng.integers(1, 50, n).astype(np.int64))})))
    catalog.register(MemoryTable("part", pa.table({
        "pk": pa.array(np.arange(200, dtype=np.int64)),
        "grp": pa.array(["g%d" % (i % 12) for i in range(200)])})))
    catalog.register(MemoryTable("supp", pa.table({
        "sk": pa.array(np.arange(50, dtype=np.int64)),
        "nat": pa.array(["n%d" % (i % 7) for i in range(50)])})))
    config = BallistaConfig({
        "ballista.shuffle.partitions": str(partitions),
        "ballista.join.broadcast_threshold": "0",
    })
    sql = ("select p.grp, s.nat, count(*) as n, sum(l.qty) as q "
           "from li l join part p on l.pk = p.pk "
           "join supp s on l.sk = s.sk "
           "group by p.grp, s.nat order by p.grp, s.nat")
    logical = optimize(SqlToRel(catalog).plan(parse_sql(sql)))
    return PhysicalPlanner(catalog, config).plan_query(logical).plan


def _drive_preferring(graph, order, executor="exec-0"):
    """Drain the graph, completing poppable tasks stage-by-stage in the
    priority given by ``order`` (stages not listed go last)."""
    for _ in range(400):
        if graph.status != "running":
            return
        pool = []
        while True:
            t = graph.pop_next_task(executor)
            if t is None:
                break
            pool.append(t)
        assert pool, f"graph stalled: {graph!r}"
        pool.sort(key=lambda d: order.index(d.task.stage_id)
                  if d.task.stage_id in order else len(order))
        for d in pool:
            graph.update_task_status([fake_success(d, executor)])
    raise AssertionError("graph never finished")


def test_broadcast_switch_keeps_resolved_nonleaf_probe_exchange():
    """Regression: plan resolution mutates stage plans IN PLACE, so a
    probe exchange that already resolved reads its upstreams through baked
    ShuffleReaderExecs.  Grafting that subtree used to sever the lineage
    (orphaned producer stages -> PlanValidationError at absorption time).
    The switch must still flip the join but keep the exchange stage."""
    graph = ExecutionGraph.build("j", three_join_plan(partitions=4))
    join2 = next(j for s in graph.stages.values()
                 for j in collect_nodes(s.plan, JoinExec)
                 if isinstance(j.left, UnresolvedShuffleExec)
                 and graph.stages[j.left.stage_id].producer_ids)
    consumer = next(s for s in graph.stages.values()
                    if join2 in collect_nodes(s.plan, JoinExec))
    probe_sid, build_sid = join2.left.stage_id, join2.right.stage_id
    probe_producers = list(graph.stages[probe_sid].producer_ids)

    # complete the probe exchange's own producers first so it resolves in
    # place, THEN let the small build side finish — the order that used to
    # orphan the probe subtree.
    _drive_preferring(graph, probe_producers + [build_sid])
    assert graph.status == "successful"
    assert join2.dist == "broadcast"
    assert probe_sid in graph.stages, "resolved exchange must be kept"
    assert probe_sid in consumer.producer_ids
    for pid in probe_producers:
        assert pid in graph.stages, f"producer stage {pid} orphaned"
    [rec] = [r for r in consumer.aqe_rewrites if r["kinds"] == ["broadcast"]]
    assert rec["build_stage_id"] == build_sid
    assert rec["grafted_stage_id"] is None


def test_three_join_chain_succeeds_under_any_leaf_order():
    """Every leaf-completion order must drain to success (three of the six
    used to crash absorption with orphaned stages before the graft guard)."""
    leaves = [s.stage_id for s in
              ExecutionGraph.build("j", three_join_plan(4)).stages.values()
              if not s.producer_ids]
    assert len(leaves) == 3
    for order in itertools.permutations(leaves):
        graph = ExecutionGraph.build("j", three_join_plan(partitions=4))
        _drive_preferring(graph, list(order))
        assert graph.status == "successful", f"order {order} failed"


# --------------------------------------------------------------------------
# failpoint window
# --------------------------------------------------------------------------

def test_failpoint_drop_skips_rewrite():
    faults.install(faults.FaultPlan([faults.FaultRule(
        FAILPOINT, "drop")], seed=1))
    try:
        graph = ExecutionGraph.build("j", physical_plan(partitions=8))
        graph.aqe = AqePolicy(coalesce_target_rows=1700,
                              coalesce_target_bytes=0,
                              skew_enabled=False, broadcast_enabled=False)
        pump_until(graph, lambda: graph.stages[2].state == RUNNING,
                   hooks={1: sized_success({q: 100 for q in range(8)},
                                           bytes_per_row=1)})
        stage2 = graph.stages[2]
        assert stage2.partitions == 8, "dropped rewrite must not mutate"
        assert stage2.aqe_rewrites == []
        drain(graph)
        assert graph.status == "successful"
        assert faults.active().schedule(), "failpoint must have fired"
    finally:
        faults.clear()


def test_failpoint_raise_degrades_to_noop():
    faults.install(faults.FaultPlan([faults.FaultRule(
        FAILPOINT, "raise", error="io",
        message="injected aqe fault")], seed=1))
    try:
        graph = ExecutionGraph.build("j", physical_plan(partitions=8))
        graph.aqe = AqePolicy(coalesce_target_rows=1700,
                              coalesce_target_bytes=0,
                              skew_enabled=False, broadcast_enabled=False)
        pump_until(graph, lambda: graph.stages[2].state == RUNNING,
                   hooks={1: sized_success({q: 100 for q in range(8)},
                                           bytes_per_row=1)})
        assert graph.stages[2].partitions == 8
        drain(graph)
        assert graph.status == "successful", \
            "an injected rewrite fault must never fail the job"
    finally:
        faults.clear()


# --------------------------------------------------------------------------
# checkpoint / recovery of the mutated graph
# --------------------------------------------------------------------------

def test_serde_roundtrip_preserves_coalesced_stage():
    graph = ExecutionGraph.build("j", physical_plan(partitions=8))
    graph.aqe = AqePolicy(coalesce_target_rows=1700, coalesce_target_bytes=0,
                          skew_enabled=False, broadcast_enabled=False)
    pump_until(graph, lambda: graph.stages[2].state == RUNNING,
               hooks={1: sized_success({q: 100 for q in range(8)},
                                       bytes_per_row=1)})
    assert graph.stages[2].partitions == 4

    rec = graph_from_obj(graph_to_obj(graph))
    stage2 = rec.stages[2]
    assert stage2.partitions == 4
    assert stage2.planned_partitions == 8
    assert len(stage2.task_infos) == 4
    assert stage2.aqe_rewrites == graph.stages[2].aqe_rewrites
    assert rec.aqe == graph.aqe
    assert rec.aqe_log == graph.aqe_log
    readers = collect_nodes(stage2.resolved_plan, ShuffleReaderExec)
    for r in readers:
        assert r.partition_count == 4
        assert r._orig_partition_count == 8
    # the recovered graph must survive a rollback, which needs the
    # restored _orig_partition_count to rebuild the planned 8-way exchange
    t = rec.pop_next_task("exec-0")
    rec.update_task_status([TaskStatus(
        t.task, "exec-0", "failed",
        failure=FailedReason(FETCH_PARTITION_ERROR, "dead peer",
                             map_stage_id=1, map_partition_id=0,
                             executor_id="exec-0"))])
    assert rec.stages[2].partitions == 8
    drain(rec)
    assert rec.status == "successful"


def test_serde_roundtrip_preserves_grafted_graph():
    graph = ExecutionGraph.build("j", join_plan(partitions=4))
    graph.aqe = AqePolicy(coalesce_enabled=False, skew_enabled=False,
                          broadcast_threshold_rows=1000)
    consumer = next(s for s in graph.stages.values()
                    if collect_nodes(s.plan, JoinExec))
    join = collect_nodes(consumer.plan, JoinExec)[0]
    probe_sid, build_sid = join.left.stage_id, join.right.stage_id
    hold_probe_finish_build(graph, probe_sid, build_sid)
    assert probe_sid not in graph.stages

    rec = graph_from_obj(graph_to_obj(graph))
    assert probe_sid not in rec.stages
    rstage = rec.stages[consumer.stage_id]
    rjoin = collect_nodes(rstage.resolved_plan or rstage.plan, JoinExec)[0]
    assert rjoin.dist == "broadcast"
    assert rstage.aqe_rewrites == consumer.aqe_rewrites
    drain(rec)
    assert rec.status == "successful"


def test_pre_aqe_checkpoint_still_loads():
    """A checkpoint written before this feature (no aqe keys) must load."""
    graph = ExecutionGraph.build("j", physical_plan(partitions=4))
    obj = graph_to_obj(graph)
    obj.pop("aqe"), obj.pop("aqe_log")
    for st in obj["stages"]:
        st.pop("partitions"), st.pop("orig_partitions"), st.pop("aqe_rewrites")
    rec = graph_from_obj(obj)
    drain(rec)
    assert rec.status == "successful"


# --------------------------------------------------------------------------
# policy plumbing + observability
# --------------------------------------------------------------------------

def test_policy_from_config():
    cfg = BallistaConfig({
        "ballista.aqe.enabled": "true",
        "ballista.aqe.coalesce.target.rows": "123",
        "ballista.aqe.broadcast.enabled": "false",
        "ballista.aqe.skew.factor": "7.5",
    })
    p = AqePolicy.from_config(cfg)
    assert p.enabled is True
    assert p.coalesce_target_rows == 123
    assert p.broadcast_enabled is False
    assert p.skew_factor == 7.5
    assert AqePolicy.from_config(None) == AqePolicy()


def test_stats_and_dot_carry_rewrite_annotations():
    from arrow_ballista_tpu.obs.stats import explain_analyze_report
    from arrow_ballista_tpu.scheduler.graph_dot import graph_to_dot

    graph = ExecutionGraph.build("j", physical_plan(partitions=8))
    graph.aqe = AqePolicy(coalesce_target_rows=1700, coalesce_target_bytes=0,
                          skew_enabled=False, broadcast_enabled=False)
    pump_until(graph, lambda: graph.stages[2].state == RUNNING,
               hooks={1: sized_success({q: 100 for q in range(8)},
                                       bytes_per_row=1)})
    drain(graph)
    report = explain_analyze_report(graph)
    s2 = next(s for s in report["stages"] if s["stage_id"] == 2)
    assert s2["aqe"] and s2["aqe"][0]["kinds"] == ["coalesce"]
    assert "aqe coalesce 8->4" in report["text"]
    dot = graph_to_dot(graph)
    assert "aqe coalesce 8->4" in dot
