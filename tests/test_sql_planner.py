"""SQL front-end tests: parse + logical plan + optimize over TPC-H schemas.

Mirrors the reference's planner snapshot tests
(reference ballista/scheduler/src/planner.rs:330-646) at the logical level.
"""
import pytest

from arrow_ballista_tpu.models import logical as L
from arrow_ballista_tpu.sql.optimizer import optimize
from arrow_ballista_tpu.sql.parser import parse_sql
from arrow_ballista_tpu.sql.planner import Catalog, SqlToRel
from arrow_ballista_tpu.utils.errors import PlanningError
from benchmarks.schema import TABLES


class TpchCatalog(Catalog):
    def table_schema(self, name):
        if name not in TABLES:
            raise PlanningError(f"table not found: {name}")
        return TABLES[name]

    def table_names(self):
        return list(TABLES)


def plan(sql, opt=True):
    p = SqlToRel(TpchCatalog()).plan(parse_sql(sql))
    return optimize(p) if opt else p


def collect(plan_node, kind):
    out = []
    def walk(p):
        if isinstance(p, kind):
            out.append(p)
        for c in p.children():
            walk(c)
    walk(plan_node)
    return out


def test_q1_plan_shape():
    p = plan("""select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
        avg(l_discount) as avg_disc, count(*) as count_order
        from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
        group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus""")
    scans = collect(p, L.TableScan)
    assert len(scans) == 1
    # filter pushed into scan, projection pruned to needed columns
    assert scans[0].filters, "shipdate filter should be pushed into the scan"
    assert set(scans[0].projection) == {
        "l_returnflag", "l_linestatus", "l_quantity", "l_discount", "l_shipdate"}
    aggs = collect(p, L.Aggregate)
    assert len(aggs) == 1
    assert len(aggs[0].group_exprs) == 2
    sorts = collect(p, L.Sort)
    assert len(sorts) == 1
    assert p.schema.names() == [
        "l_returnflag", "l_linestatus", "sum_qty", "avg_disc", "count_order"]


def test_q3_join_graph():
    p = plan("""select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
        o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey and l_orderkey = o_orderkey
          and o_orderdate < date '1995-03-15' and l_shipdate > date '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate limit 10""")
    joins = collect(p, L.Join)
    assert len(joins) == 2
    assert all(j.join_type == "inner" for j in joins)
    assert not collect(p, L.CrossJoin), "join graph should avoid cross joins"
    limits = collect(p, L.Limit)
    assert limits and limits[0].n == 10
    # selective filters pushed to each scan
    scans = {s.table: s for s in collect(p, L.TableScan)}
    assert scans["customer"].filters
    assert scans["orders"].filters
    assert scans["lineitem"].filters


def test_q18_in_subquery_becomes_semi_join():
    p = plan("""select c_name, sum(l_quantity) from customer, orders, lineitem
        where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                             having sum(l_quantity) > 300)
          and c_custkey = o_custkey and o_orderkey = l_orderkey
        group by c_name""")
    joins = collect(p, L.Join)
    assert any(j.join_type == "semi" for j in joins)


def test_q21_exists_and_not_exists():
    p = plan("""select s_name, count(*) as numwait from supplier, lineitem l1, orders, nation
        where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey and o_orderstatus = 'F'
          and l1.l_receiptdate > l1.l_commitdate
          and exists (select * from lineitem l2 where l2.l_orderkey = l1.l_orderkey
                      and l2.l_suppkey <> l1.l_suppkey)
          and not exists (select * from lineitem l3 where l3.l_orderkey = l1.l_orderkey
                      and l3.l_suppkey <> l1.l_suppkey and l3.l_receiptdate > l3.l_commitdate)
          and s_nationkey = n_nationkey and n_name = 'SAUDI ARABIA'
        group by s_name order by numwait desc, s_name limit 100""")
    kinds = [j.join_type for j in collect(p, L.Join)]
    # r5: both EXISTS subqueries decorrelate into grouped min/max
    # aggregates (SqlToRel._exists_minmax_rewrite) — EXISTS becomes an
    # inner join + filter, NOT EXISTS a left join + IS NULL/equality
    # filter; no semi/anti pair-explosion joins remain
    assert "semi" not in kinds and "anti" not in kinds
    assert "left" in kinds
    aggs = collect(p, L.Aggregate)
    minmax = [a for a in aggs
              if any(x.func in ("min", "max") for x, _ in a.agg_exprs)]
    assert len(minmax) >= 2  # one per EXISTS subquery


def test_q2_correlated_scalar_decorrelates():
    p = plan("""select s_acctbal, s_name, p_partkey from part, supplier, partsupp, nation, region
        where p_partkey = ps_partkey and s_suppkey = ps_suppkey and p_size = 15
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey and r_name = 'EUROPE'
          and ps_supplycost = (select min(ps_supplycost) from partsupp, supplier, nation, region
             where p_partkey = ps_partkey and s_suppkey = ps_suppkey
               and s_nationkey = n_nationkey and n_regionkey = r_regionkey and r_name = 'EUROPE')
        order by s_acctbal desc limit 100""")
    aggs = collect(p, L.Aggregate)
    assert len(aggs) == 1, "correlated min() should become a grouped subplan"
    assert len(aggs[0].group_exprs) == 1


def test_ambiguous_column_rejected():
    with pytest.raises(PlanningError, match="ambiguous"):
        plan("select l_orderkey from lineitem l1, lineitem l2 where l1.l_orderkey = l2.l_orderkey")


def test_unknown_column_rejected():
    with pytest.raises(PlanningError, match="not found"):
        plan("select bogus_col from lineitem")


def test_decimal_scale_propagation():
    p = plan("select sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as s from lineitem")
    f = p.schema.field("s")
    assert f.dtype.kind == "decimal" and f.dtype.scale == 6


def test_explicit_join_on():
    p = plan("""select n_name, count(*) from customer
        join nation on c_nationkey = n_nationkey group by n_name""")
    joins = collect(p, L.Join)
    assert len(joins) == 1 and joins[0].on


def test_derived_table():
    p = plan("""select cntrycode, count(*) from (
        select substring(c_phone from 1 for 2) as cntrycode from customer) as t
        group by cntrycode""")
    assert collect(p, L.Aggregate)


def test_auto_shuffle_partitions():
    """'auto' derives the shuffle partition count from the largest scanned
    table so per-task batches stay near the configured capacity (the
    memory-control heuristic; reference has no equivalent)."""
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.scheduler.physical_planner import PhysicalPlanner
    from arrow_ballista_tpu.sql.optimizer import optimize
    from arrow_ballista_tpu.utils.config import BallistaConfig

    ctx = BallistaContext.local(BallistaConfig({
        "ballista.shuffle.partitions": "auto",
        "ballista.batch.size": str(1 << 10),
    }))
    n = 5000  # -> ceil(5000/1024) = 5 partitions
    ctx.register_table("t", pa.table({
        "g": np.arange(n, dtype=np.int64) % 7,
        "v": np.ones(n, dtype=np.int64)}))
    df = ctx.sql("select g, sum(v) s from t group by g order by g")
    planner = PhysicalPlanner(ctx.catalog, ctx.config)
    planner.plan_query(optimize(df.logical))
    assert planner.partitions == 5
    # and the query still runs end to end
    out = df.to_pandas()
    assert len(out) == 7 and out.s.sum() == n


def test_explain_statement_local():
    """EXPLAIN <select> returns DataFusion-shaped plan rows."""
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    ctx.register_table("t", pa.table({"g": np.arange(50) % 3,
                                      "v": np.ones(50, dtype=np.int64)}))
    out = ctx.sql("EXPLAIN select g, sum(v) s from t group by g order by g").to_pandas()
    assert out.plan_type.tolist() == ["logical_plan", "physical_plan"]
    assert "Aggregate" in out.plan.iloc[0]
    assert "HashAggregateExec" in out.plan.iloc[1]
    # catalog stays clean — EXPLAIN must not leak temp tables
    assert not [n for n in ctx.catalog.table_names() if n.startswith("__")]
    # VERBOSE adds the distributed stage split
    out2 = ctx.sql("EXPLAIN VERBOSE select g, sum(v) s from t group by g").to_pandas()
    assert out2.plan_type.tolist() == [
        "logical_plan", "physical_plan", "distributed_plan"]
    assert "Stage" in out2.plan.iloc[2] and "ShuffleWriterExec" in out2.plan.iloc[2]


def test_docs_configs_fresh():
    """docs/user-guide/configs.md must match the live config registry."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "docs", "gen_configs.py"),
         "--check"],
        capture_output=True, text=True, cwd=repo)
    assert r.returncode == 0, r.stderr


def test_set_statement_local_and_remote():
    """SET key = value configures the session through SQL in both modes
    (reference: DataFusion SET via ballista-cli)."""
    import tempfile

    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

    ctx = BallistaContext.local()
    ctx.sql("SET ballista.shuffle.partitions = 3")
    assert ctx.config.shuffle_partitions == 3
    ctx.sql("SET ballista.shuffle.partitions = 'auto'")
    assert ctx.config.shuffle_partitions == 0
    ctx.sql("SET ballista.shuffle.mesh = true")
    from arrow_ballista_tpu.utils.config import MESH_SHUFFLE
    assert ctx.config.get(MESH_SHUFFLE) is True
    # signed numeric values lex as op + number — must parse (advisor find)
    from arrow_ballista_tpu.sql.parser import parse_sql as _parse
    assert _parse("SET ballista.x = -1").value == "-1"
    assert _parse("SET ballista.x = +120").value == "120"
    import pytest as _pytest
    from arrow_ballista_tpu.utils.errors import ConfigurationError
    with _pytest.raises(ConfigurationError):
        ctx.sql("SET no.such.key = 1")

    svc = SchedulerNetService("127.0.0.1", 0, rest_port=None)
    svc.start()
    ex = ExecutorServer("127.0.0.1", svc.port, "127.0.0.1", 0,
                        work_dir=tempfile.mkdtemp())
    ex.start()
    try:
        rctx = BallistaContext.remote("127.0.0.1", svc.port)
        rctx.sql("SET ballista.shuffle.partitions = 2")
        # the scheduler session planned with the updated value: partition
        # count shows up in the distributed plan row
        rctx.register_table("t", pa.table({"a": np.arange(100, dtype=np.int64),
                                           "g": np.arange(100, dtype=np.int64) % 4}))
        plan = rctx.sql("EXPLAIN select g, sum(a) s from t group by g"
                        ).to_pandas().plan.iloc[1]
        assert "hash[2]" in plan, plan
        shown = rctx.sql("SHOW ballista.shuffle.partitions").to_pandas()
        assert shown.value.tolist() == ["2"]
        out = rctx.sql("select sum(a) s from t").to_pandas()
        assert int(out.s.iloc[0]) == 4950
        rctx.shutdown()
    finally:
        ex.stop()
        svc.stop()


def test_show_settings():
    """SHOW ALL / SHOW <key> pair with SET (DataFusion parity)."""
    import pytest as _pytest

    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.errors import ConfigurationError

    ctx = BallistaContext.local()
    ctx.sql("SET ballista.shuffle.partitions = 9")
    out = ctx.sql("SHOW ballista.shuffle.partitions").to_pandas()
    assert out.value.tolist() == ["9"]
    allv = ctx.sql("SHOW ALL").to_pandas()
    assert "ballista.batch.size" in set(allv.name)
    assert dict(zip(allv.name, allv.value))["ballista.shuffle.partitions"] == "9"
    with _pytest.raises(ConfigurationError):
        ctx.sql("SHOW no.such.key")


def test_describe_statement():
    """DESCRIBE/DESC t == SHOW COLUMNS FROM t (DataFusion parity)."""
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    ctx.register_table("t", pa.table({"a": np.arange(5, dtype=np.int64)}))
    out = ctx.sql("DESCRIBE t").to_pandas()
    assert out.column_name.tolist() == ["a"] and out.data_type.tolist() == ["int64"]
    assert ctx.sql("desc t").to_pandas().equals(out)


def test_order_by_qualified_grouped_column():
    """ORDER BY a qualified column that the select list exposes unaliased
    (``select d.w ... group by d.w order by d.w``): the aggregate rewrite
    renames select exprs to agg outputs, so matching must also consult the
    pre-aggregation resolution."""
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu.client.context import BallistaContext

    ctx = BallistaContext.local()
    rng = np.random.default_rng(0)
    ctx.register_table("t", pa.table({
        "k": pa.array(rng.integers(0, 5, 100).astype(np.int64)),
        "v": pa.array(rng.integers(0, 9, 100).astype(np.int64))}))
    ctx.register_table("d", pa.table({
        "k": pa.array(np.arange(5, dtype=np.int64)),
        "w": pa.array(np.arange(5, dtype=np.int64) * 2)}))
    out = ctx.sql("select d.w, sum(t.v) s from t join d on t.k = d.k "
                  "group by d.w order by d.w desc").to_pandas()
    assert out.w.tolist() == [8, 6, 4, 2, 0]


def test_three_table_explicit_join_chain(tmp_path):
    """a JOIN b ON .. JOIN c ON .. nests composite relations; every member
    alias must stay resolvable in the SELECT scope (r5 regression)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from arrow_ballista_tpu.client.context import BallistaContext

    d = tmp_path
    pq.write_table(pa.table({"pk": np.arange(10, dtype=np.int64),
                             "sk": np.arange(10, dtype=np.int64) % 3,
                             "qty": np.ones(10, dtype=np.int64)}),
                   str(d / "li.parquet"))
    pq.write_table(pa.table({"pk": np.arange(10, dtype=np.int64),
                             "grp": np.array(["g%d" % (i % 2) for i in range(10)])}),
                   str(d / "part.parquet"))
    pq.write_table(pa.table({"sk": np.arange(3, dtype=np.int64),
                             "nat": np.array(["n0", "n1", "n2"])}),
                   str(d / "supp.parquet"))
    ctx = BallistaContext.local()
    for t in ("li", "part", "supp"):
        ctx.register_parquet(t, str(d / f"{t}.parquet"))
    out = ctx.sql(
        "select p.grp, s.nat, sum(l.qty) as q from li l "
        "join part p on l.pk = p.pk join supp s on l.sk = s.sk "
        "group by p.grp, s.nat order by p.grp, s.nat").to_pandas()
    assert out.q.sum() == 10
    assert set(out.grp) == {"g0", "g1"}
