"""Headline benchmark: TPC-H q1 pipeline throughput on one chip.

Runs the flagship fused query step (filter -> derived columns -> grouped
aggregate, the TPC-H q1 execution shape) over synthetic lineitem-shaped
data resident in HBM, and reports rows/sec.

Baseline: the reference's README chart puts Ballista 0.11 at ~3.1 s for
q1 at SF10 (~59.99M lineitem rows) on a 24-core single-node executor
(reference README.md:52-60, BASELINE.md) => ~19.35M rows/s.
``vs_baseline`` = our rows/s divided by that.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}
"""
from __future__ import annotations

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

BASELINE_ROWS_PER_S = 59_986_052 / 3.1  # reference q1 SF10 wall-clock

ROWS = 8_000_000
ITERS = 5


def main() -> None:
    from __graft_entry__ import _q1_augment, _q1_example, _q1_filter, _Q1_AGGS, _Q1_KEYS
    from arrow_ballista_tpu.ops import kernels as K

    cols_np, mask_np = _q1_example(ROWS, seed=7)
    cols = {k: jax.device_put(jnp.asarray(v)) for k, v in cols_np.items()}
    mask = jax.device_put(jnp.asarray(mask_np))

    @jax.jit
    def step(cols, mask):
        cols, mask = _q1_filter(cols, mask)
        cols = _q1_augment(cols)
        keys = [cols[k] for k in _Q1_KEYS]
        vals = [(cols[v], how) for v, how in _Q1_AGGS]
        return K.grouped_aggregate(keys, vals, mask, 16)

    # warmup / compile
    out = step(cols, mask)
    jax.block_until_ready(out[1])

    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        out = step(cols, mask)
        jax.block_until_ready(out[1])
        times.append(time.perf_counter() - t0)

    elapsed = float(np.median(times))
    rows_per_s = ROWS / elapsed
    print(json.dumps({
        "metric": "tpch_q1_pipeline_rows_per_sec",
        "value": round(rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_s / BASELINE_ROWS_PER_S, 3),
    }))


if __name__ == "__main__":
    main()
