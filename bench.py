"""Headline benchmark: TPC-H through the engine on one chip.

Two layers, both reported:

- **engine**: TPC-H q1/q6/q3/q5/q18 at SF1 run END-TO-END through
  ``BallistaContext.standalone`` — parquet scan -> device pipeline ->
  shuffle -> final aggregate -> collect.  The headline metric is engine
  rows/s on q1 (lineitem rows / wall-clock), matching how the reference's
  README chart is computed (reference README.md:52-60: q1 SF10 in ~3.1 s on
  a 24-core executor => ~19.35M rows/s, see BASELINE.md).  When SF10 data
  exists the like-for-like SF10 numbers become the headline.
- **kernel**: the fused q1 pipeline (filter -> derived columns -> grouped
  aggregate) over HBM-resident arrays, isolating device throughput from IO.

Reliability design (rounds 1-4 failure mode: the experimental "axon" TPU
plugin's tunnel can hang backend init for 900s+, and serial tpu-then-cpu
attempts burned the whole budget before any number landed):

- The parent never imports jax.  It runs TWO workers CONCURRENTLY:
  a CPU-forced worker (axon plugin disabled at the env level — it can
  never hang) and a TPU worker under an init watchdog.
- TPU backend init is supervised: if the "backend up" marker doesn't
  appear within BENCH_INIT_TIMEOUT the attempt is killed and retried
  with backoff while the TPU budget lasts.  A worker that initialized
  once holds its lease for the whole run (warm lease reuse).
- Workers print a RESULT JSON line after every milestone (backend up,
  platform constants, each query, each transport); the parent re-prints
  the best merged JSON line every time one improves.  Even a truncated
  run leaves TPU evidence on stdout and in .bench_logs/latest.json.
- The TPU worker waits at a gate before its host-heavy engine phase
  until the CPU worker finishes (this box has ONE core; running both
  engine benches concurrently would corrupt the CPU numbers).  Device-
  bound phases (platform constants, kernel microbench) run before the
  gate, so TPU evidence lands early.

The FINAL stdout line is the merged result:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N, ...}
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_ROWS_PER_S = 59_986_052 / 3.1  # reference q1 SF10 wall-clock
SCALE = float(os.environ.get("BENCH_SCALE", "1"))
QUERIES = os.environ.get("BENCH_QUERIES", "1,6,3,5,18")
MESH_QUERIES = os.environ.get("BENCH_MESH_QUERIES", "1,6,3")
SF10_QUERIES = os.environ.get("BENCH_SF10_QUERIES", "1,3,5,18")
# iteration knobs: drop to 1 to trade steady-state fidelity for budget
ITERS = int(os.environ.get("BENCH_ITERS", "2"))
SF10_ITERS = int(os.environ.get("BENCH_SF10_ITERS", "2"))
DATA_DIR = os.environ.get(
    "BENCH_DATA", os.path.join(REPO, ".bench_data", f"tpch-sf{SCALE:g}")
)
KERNEL_ROWS = int(os.environ.get("BENCH_KERNEL_ROWS", str(8_000_000)))
LOG_DIR = os.path.join(REPO, ".bench_logs")


def _cpu_env(n_devices: int = 1) -> dict:
    # single definition of "CPU-forced, TPU-plugin-free" lives next to the
    # other driver entry point
    sys.path.insert(0, REPO)
    from __graft_entry__ import _scrubbed_cpu_env

    return _scrubbed_cpu_env(n_devices)


def ensure_data() -> None:
    marker = os.path.join(DATA_DIR, "lineitem.parquet")
    if os.path.exists(marker):
        return
    os.makedirs(DATA_DIR, exist_ok=True)
    print(f"[bench] generating TPC-H SF{SCALE:g} under {DATA_DIR}", file=sys.stderr)
    subprocess.run(
        [sys.executable, "-m", "benchmarks.tpch", "convert",
         "--scale", str(SCALE), "--output", DATA_DIR],
        cwd=REPO, env=_cpu_env(), check=True, timeout=1800,
        stdout=sys.stderr,
    )


# --------------------------------------------------------------------------
# worker (runs in a subprocess; the only place jax is imported)
# --------------------------------------------------------------------------


def _worker(platform: str, gate_file: str | None, deadline: float) -> None:
    import numpy as np
    import jax

    # int64 columns (fixed-point decimals, keys) need x64; the device path
    # never produces f64 arrays (divisions are host-finalize), so this is
    # TPU-safe
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"[worker] backend up: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    result: dict = {
        "metric": f"tpch_q1_sf{SCALE:g}_engine_rows_per_sec",
        "value": 0.0, "unit": "rows/s", "vs_baseline": 0.0,
        "partial": "backend-up",
        "platform": dev.platform, "device": str(dev.device_kind),
    }

    def emit(stage: str) -> None:
        """Milestone emission: every print is a complete, parseable result —
        the parent takes the newest line, so a killed worker still leaves
        everything measured so far."""
        result["partial"] = stage
        print(json.dumps(result), flush=True)

    emit("backend-up")

    # --- platform characterization: the constants needed to interpret the
    # engine numbers (the device may sit across a network tunnel where
    # per-op latency, not FLOPs, dominates) -----------------------------
    def _med(f, n=5):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            f()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    small = np.zeros(128, np.int32)
    big = np.zeros(8 << 20, np.int64)  # 64 MB
    d_small = jax.device_put(small)
    jax.block_until_ready(d_small)
    tiny = jax.jit(lambda x: x + 1)
    jax.block_until_ready(tiny(d_small))
    rtt = _med(lambda: jax.block_until_ready(tiny(d_small)))
    h2d = _med(lambda: jax.block_until_ready(jax.device_put(big)), 3)
    # d2h must use a FRESH device array per iteration: ArrayImpl caches the
    # first host copy (_npy_value), so re-reading the same array measures a
    # cache hit, not the transfer
    d_bigs = [jax.device_put(tiny(jax.device_put(big))) for _ in range(3)]
    jax.block_until_ready(d_bigs)
    it = iter(d_bigs)
    d2h = _med(lambda: np.asarray(next(it)), 3)
    result["platform_rtt_ms"] = round(rtt * 1000, 2)
    result["platform_h2d_gbps"] = round(big.nbytes / h2d / 1e9, 2)
    result["platform_d2h_gbps"] = round(big.nbytes / d2h / 1e9, 2)
    print(f"[worker] platform: rtt {rtt*1000:.2f} ms, "
          f"h2d {big.nbytes/h2d/1e9:.2f} GB/s, d2h {big.nbytes/d2h/1e9:.2f} GB/s",
          file=sys.stderr)
    del d_bigs, big
    emit("platform-constants")

    # --- kernel microbench ---------------------------------------------
    sys.path.insert(0, REPO)
    from __graft_entry__ import _q1_augment, _q1_example, _q1_filter, _Q1_AGGS, _Q1_KEYS
    from arrow_ballista_tpu.ops import kernels as K

    cols_np, mask_np = _q1_example(KERNEL_ROWS, seed=7)
    cols = {k: jax.device_put(jnp.asarray(v)) for k, v in cols_np.items()}
    mask = jax.device_put(jnp.asarray(mask_np))

    # key_ranges mirrors the engine: returnflag/linestatus are dict-coded
    # strings with host-known code ranges, which selects the dense sort-free
    # grouping path (kernels.grouped_aggregate) — the path engine q1 runs
    @jax.jit
    def step(cols, mask):
        cols, mask = _q1_filter(cols, mask)
        cols = _q1_augment(cols)
        keys = [cols[k] for k in _Q1_KEYS]
        vals = [(cols[v], how) for v, how in _Q1_AGGS]
        return K.grouped_aggregate(keys, vals, mask, 16,
                                   key_ranges=((0, 2), (0, 1)))

    t_c = time.perf_counter()
    out = step(cols, mask)  # compile + warmup
    jax.block_until_ready(out)
    result["kernel_q1_compile_s"] = round(time.perf_counter() - t_c, 1)
    # block on the WHOLE output tree AND force a 1-element host read: an
    # experimental remote backend's block_until_ready may not await remote
    # completion, and a D2H read cannot lie (its cost is one rtt, reported
    # above for subtraction)
    def _timed_step():
        out = step(cols, mask)
        jax.block_until_ready(out)
        # tiny D2H read (16-slot group mask): completion proof — overflow
        # (out[3]) is None on the dense path since it became statically
        # impossible there
        np.asarray(out[2])

    med = _med(_timed_step, 10)
    kernel_rows_s = KERNEL_ROWS / med
    # sanity companion: effective HBM read bandwidth implied by the input
    # columns alone — if this exceeds the chip's spec the measurement is
    # wrong, not the kernel fast
    in_bytes = sum(v.nbytes for v in cols.values()) + mask.nbytes
    result["kernel_q1_rows_per_sec"] = round(kernel_rows_s, 1)
    result["kernel_q1_ms"] = round(med * 1000, 3)
    result["kernel_q1_gbps"] = round(in_bytes / med / 1e9, 1)
    print(f"[worker] kernel q1: {kernel_rows_s/1e6:.1f}M rows/s "
          f"({med*1000:.2f} ms, {in_bytes/med/1e9:.0f} GB/s implied)",
          file=sys.stderr)
    del cols, mask, out
    emit("kernel-q1")

    # --- gate: wait for the CPU worker before the host-heavy engine phase
    # (one core; concurrent engine benches would corrupt both).  The lease
    # stays warm while waiting — that is the point.
    if gate_file:
        gate_wait = float(os.environ.get("BENCH_GATE_WAIT", "2400"))
        t_g = time.time()
        while not os.path.exists(gate_file) and time.time() - t_g < gate_wait:
            time.sleep(5)
        print(f"[worker] gate cleared after {time.time()-t_g:.0f}s",
              file=sys.stderr)

    # --- engine bench: TPC-H through BallistaContext --------------------
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.config import BallistaConfig
    from benchmarks.queries import QUERIES as SQL
    from benchmarks.tpch import register_tables

    # ONE base config shared by the file and mesh runs so the two transports
    # stay knob-for-knob comparable
    base_config = {
        # auto -> ceil(rows/batch) partitions; measured best on SF1 (6 for
        # the 12-row-group lineitem: 2 row groups per scan task)
        "ballista.shuffle.partitions": "auto",
        "ballista.batch.size": str(1 << 20),
        # engine deadline: generous (slow first-compile runs must finish) but
        # below the parent's subprocess timeout so the engine fails first
        # with a real error instead of a SIGKILL
        "ballista.job.timeout.seconds": "1800",
    }
    def _warm_cache(paths, label):
        # warm the OS page cache first: whichever run goes first would
        # otherwise pay cold disk reads the others don't (observed: file
        # q1 7.3 s cold vs 3.0 s warm on the same code)
        t_w = time.perf_counter()
        for path in paths:
            with open(path, "rb") as fh:
                while fh.read(1 << 24):
                    pass
        print(f"[worker] {label} page-cache warmup: "
              f"{time.perf_counter()-t_w:.1f}s", file=sys.stderr)

    _warm_cache([os.path.join(DATA_DIR, f)
                 for f in sorted(os.listdir(DATA_DIR))
                 if f.endswith(".parquet")], "sf1")

    ctx = BallistaContext.standalone(BallistaConfig(dict(base_config)),
                                     concurrent_tasks=4)
    register_tables(ctx, DATA_DIR)
    lineitem_rows = ctx.catalog.provider("lineitem").row_count()
    result["lineitem_rows"] = lineitem_rows

    def _job_metrics(ctx):
        """Aggregate per-operator metrics of the most recent job, per stage —
        every bench run doubles as a profile (the round-2 lesson: a failed
        run with no metrics tells you nothing about WHERE the time went)."""
        try:
            sched = ctx._standalone.scheduler
            jobs = list(sched.jobs._status)
            if not jobs:
                return {}
            graph = sched.jobs.get_graph(jobs[-1])
            out = {}
            for sid in sorted(graph.stages):
                s = graph.stages[sid]
                spans = []
                for t in s.task_infos:
                    if not t or not t.status:
                        continue
                    st = t.status
                    if st.start_time_ms and st.end_time_ms:
                        spans.append((st.start_time_ms, st.end_time_ms))
                entry = {k: round(v, 2)
                         for k, v in sorted(s.aggregate_metrics().items())
                         if v >= 0.05}
                if spans:
                    entry["stage_wall_s"] = round(
                        (max(b for _, b in spans) - min(a for a, _ in spans))
                        / 1000, 2)
                out[f"stage{sid}"] = entry
            return out
        except Exception as e:  # noqa: BLE001 — profiling must never kill a bench
            return {"error": str(e)}

    def _headline_from_q1(engine, rows, sf_label):
        q1_s = engine.get("q1_ms", 0.0) / 1000.0
        if q1_s:
            value = rows / q1_s
            result["metric"] = f"tpch_q1_{sf_label}_engine_rows_per_sec"
            result["value"] = round(value, 1)
            result["vs_baseline"] = round(value / BASELINE_ROWS_PER_S, 4)

    def _stage_breakdown(ctx):
        """Compact per-stage runtime stats of the most recent job, read off
        the graph's RuntimeStatsStore fold (obs/stats.py): rows/bytes
        shuffled, partition skew, and task-duration p50/max.  Lands in the
        bench JSON so a regression is attributable to a STAGE, not just a
        query."""
        try:
            sa = ctx._standalone
            graph = sa.scheduler.jobs.get_graph(sa.last_job_id)
            if graph is None:
                return {}
            out = {}
            for s in graph.stats.snapshot()["stages"]:
                d = s["task_duration_s"]
                out[f"s{s['stage_id']}"] = {
                    "rows": s["output_rows"],
                    "mb": round(s["output_bytes"] / 1048576.0, 2),
                    "skew": s["skew"],
                    "p50_s": d.get("p50", 0.0),
                    "max_s": d.get("max", 0.0),
                }
            return out
        except Exception as e:  # noqa: BLE001 — profiling must never kill a bench
            return {"error": str(e)}

    def _aqe_decisions(ctx):
        """The most recent job's adaptive-rewrite decisions (scheduler/
        aqe.py's graph.aqe_log): which stages were coalesced / switched to
        broadcast / skew-split, with before/after partition counts.  Lands
        next to the stage breakdown so a perf delta is attributable to a
        plan DECISION, not just a stage."""
        try:
            sa = ctx._standalone
            graph = sa.scheduler.jobs.get_graph(sa.last_job_id)
            if graph is None:
                return []
            return [{"stage": r["stage_id"],
                     "kinds": list(r.get("kinds", ())),
                     "before": r.get("partitions_before"),
                     "after": r.get("partitions_after")}
                    for r in getattr(graph, "aqe_log", [])]
        except Exception as e:  # noqa: BLE001 — profiling must never kill a bench
            return [{"error": str(e)}]

    def _fusion_decisions(ctx):
        """The most recent job's whole-stage-compilation decisions
        (compile/fuse.py's graph.compile_log): which chains fused into one
        kernel, and which were rejected with what reason — the evidence
        that a fusion-leg delta comes from the compiler, not noise."""
        try:
            sa = ctx._standalone
            graph = sa.scheduler.jobs.get_graph(sa.last_job_id)
            if graph is None:
                return []
            return [{"stage": r["stage_id"],
                     "fused": [list(run) for run in r.get("fused_ops", ())],
                     "rejected": len(r.get("rejected", ()))}
                    for r in getattr(graph, "compile_log", [])
                    if r.get("fused")]
        except Exception as e:  # noqa: BLE001 — profiling must never kill a bench
            return [{"error": str(e)}]

    def run_queries(ctx, queries, label, dest, iters=ITERS, rows=None,
                    sf_label=None, min_slack_s=60.0):
        # min_slack_s: don't START a query with less than this left on the
        # clock — SF10 legs pass a larger slack since one iteration there
        # can run minutes (the BENCH_r05 rc=124 overrun)
        for q in queries:
            if time.time() > deadline - min_slack_s:
                dest[f"q{q}_skipped"] = "deadline"
                print(f"[worker] {label} q{q} skipped: deadline", file=sys.stderr)
                continue
            per = []
            try:
                for it in range(iters):
                    t0 = time.perf_counter()
                    res = ctx.sql(SQL[q]).collect()
                    nrows = sum(b.num_rows for b in res)
                    per.append(time.perf_counter() - t0)
                    print(f"[worker] {label} q{q} iter{it}: {per[-1]*1000:.0f} ms "
                          f"({nrows} rows)", file=sys.stderr)
                dest[f"q{q}_ms"] = round(min(per) * 1000, 1)
                dest[f"q{q}_stages"] = _stage_breakdown(ctx)
                dest[f"q{q}_aqe"] = _aqe_decisions(ctx)
                fused = _fusion_decisions(ctx)
                if fused:
                    dest[f"q{q}_fused"] = fused
                print(f"[worker] {label} q{q} metrics: "
                      f"{json.dumps(_job_metrics(ctx))}", file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — record, keep benching
                dest[f"q{q}_error"] = f"{type(e).__name__}: {e}"
                print(f"[worker] {label} q{q} FAILED: {e}", file=sys.stderr)
            if rows is not None and sf_label:
                _headline_from_q1(dest, rows, sf_label)
            emit(f"{label}-q{q}")
        return dest

    queries = [int(x) for x in QUERIES.split(",") if x.strip()]
    engine = result["engine"] = {}
    run_queries(ctx, queries, "file", engine, rows=lineitem_rows,
                sf_label=f"sf{SCALE:g}")
    ctx.shutdown()

    # --- AQE A/B leg: q1/q18 with runtime re-optimization OFF -----------
    # same iteration count as the on-leg so min-vs-min compares like with
    # like; the ratio is still order-biased (the off leg reuses the warm
    # process / XLA cache), so it's recorded as a raw ratio, not a claim
    if time.time() < deadline - 120:
        try:
            ctx_off = BallistaContext.standalone(
                BallistaConfig({**base_config,
                                "ballista.aqe.enabled": "false"}),
                concurrent_tasks=4)
            try:
                register_tables(ctx_off, DATA_DIR)
                aqe_off = result.setdefault("engine_aqe_off", {})
                run_queries(ctx_off, [q for q in (1, 18) if q in queries],
                            "aqe-off", aqe_off)
                for q in (1, 18):
                    on, off = engine.get(f"q{q}_ms"), aqe_off.get(f"q{q}_ms")
                    if on and off:
                        aqe_off[f"q{q}_off_over_on"] = round(off / on, 3)
            finally:
                ctx_off.shutdown()
        except Exception as e:  # noqa: BLE001 — A/B leg must not kill the run
            result["engine_aqe_off"] = {"error": f"{type(e).__name__}: {e}"}

    # --- fusion A/B leg: whole-stage compiler OFF ------------------------
    # q1/q18 reuse the main engine leg's fusion-ON numbers; q21 (deep
    # multi-join with a fusable filter+partial-agg leaf pipeline) gets its
    # ON number here first.  Same caveat as the AQE leg: the OFF leg runs
    # in a warm process, so the ratio is a recorded observation, not a
    # controlled claim — the stage breakdown and compile_log land next to
    # it so deltas are attributable to the fused stages specifically.
    if time.time() < deadline - 120:
        fusion_qs = [1, 18, 21]
        try:
            extra_on = [q for q in fusion_qs if not engine.get(f"q{q}_ms")]
            if extra_on:
                ctx_fon = BallistaContext.standalone(
                    BallistaConfig(dict(base_config)), concurrent_tasks=4)
                try:
                    register_tables(ctx_fon, DATA_DIR)
                    run_queries(ctx_fon, extra_on, "fusion-on", engine)
                finally:
                    ctx_fon.shutdown()
            ctx_foff = BallistaContext.standalone(
                BallistaConfig({**base_config,
                                "ballista.compile.enabled": "false"}),
                concurrent_tasks=4)
            try:
                register_tables(ctx_foff, DATA_DIR)
                fus_off = result.setdefault("engine_fusion_off", {})
                run_queries(ctx_foff, fusion_qs, "fusion-off", fus_off)
                for q in fusion_qs:
                    on = engine.get(f"q{q}_ms")
                    off = fus_off.get(f"q{q}_ms")
                    if on and off:
                        fus_off[f"q{q}_fusion_off_over_on"] = round(off / on, 3)
            finally:
                ctx_foff.shutdown()
            emit("fusion-ab")
        except Exception as e:  # noqa: BLE001 — A/B leg must not kill the run
            result["engine_fusion_off"] = {"error": f"{type(e).__name__}: {e}"}

    if not engine.get("q1_ms"):
        # a 0.0 headline must be distinguishable from a measured zero
        result["error"] = ("q1 not measured: " +
                           engine.get("q1_error", "not in BENCH_QUERIES"))
    else:
        result.pop("error", None)

    # --- SF10 rider: the reference baseline IS SF10 (README.md:52-60) ---
    # runs whenever a prior round generated the data, without making the
    # headline depend on a 13-minute generation step.  Deliberately BEFORE
    # the mesh and kernel-join legs: SF10 q1 is the headline metric, so it
    # gets first claim on whatever budget remains (BENCH_r05 ran it last
    # and timed out with no SF10 number at all)
    sf10_dir = os.path.join(REPO, ".bench_data", "tpch-sf10")
    if (SCALE == 1 and os.path.exists(os.path.join(sf10_dir, "lineitem.parquet"))
            and time.time() < deadline - 180):
        try:
            _warm_cache([os.path.join(sf10_dir, "lineitem.parquet")], "sf10")
            ctx10 = BallistaContext.standalone(
                BallistaConfig(dict(base_config)), concurrent_tasks=4)
            try:
                register_tables(ctx10, sf10_dir)
                rows10 = ctx10.catalog.provider("lineitem").row_count()
                sf10 = result.setdefault("engine_sf10", {})
                sf10_queries = [int(x) for x in SF10_QUERIES.split(",") if x.strip()]
                # warm iterations (default 2): the warm number is the steady
                # state the scan cache is designed for, and iter0 alone would
                # publish conversion-cold walls (observed: q3 80 s cold vs
                # 29 s warm).  min_slack 180 s: one SF10 iteration can run
                # minutes, so don't start one that can't finish in budget.
                run_queries(ctx10, [q for q in sf10_queries if q == 1],
                            "sf10", sf10, iters=SF10_ITERS, min_slack_s=180)
                q1_10 = sf10.get("q1_ms", 0.0) / 1000.0
                if q1_10:
                    sf10["q1_rows_per_sec"] = round(rows10 / q1_10, 1)
                    sf10["vs_baseline_sf10"] = round(
                        rows10 / q1_10 / BASELINE_ROWS_PER_S, 4)
                    # the like-for-like datapoint becomes the headline; the
                    # SF1 numbers stay in `engine`
                    result["metric"] = "tpch_q1_sf10_engine_rows_per_sec"
                    result["value"] = sf10["q1_rows_per_sec"]
                    result["vs_baseline"] = sf10["vs_baseline_sf10"]
                    emit("sf10-q1")
                run_queries(ctx10, [q for q in sf10_queries if q != 1],
                            "sf10", sf10, iters=SF10_ITERS, min_slack_s=180)
            finally:
                ctx10.shutdown()
        except Exception as e:  # noqa: BLE001 — rider must not kill the run
            result["engine_sf10"] = {"error": f"{type(e).__name__}: {e}"}

    # --- shuffle-transport A/B leg: the shuffle-heavy queries through a
    # REAL 2-executor TCP cluster (standalone's identity-local path never
    # touches the transport), one cluster per leg:
    #   mmap   — shipped defaults: host-match mmap + streaming + lz4
    #   wire   — host-match off, so co-located reads take the compressed
    #            chunked streaming path (bytes-on-wire measurement)
    #   legacy — streaming off too: the whole-file uncompressed protocol
    # DataPlaneStats is process-global and the executors are in-proc
    # threads, so snapshot deltas attribute bytes/chunks to each query.
    if time.time() < deadline - 240:
        try:
            import shutil
            import tempfile

            from arrow_ballista_tpu.executor.server import ExecutorServer
            from arrow_ballista_tpu.net import dataplane as dp
            from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService

            transport_queries = [
                int(x) for x in
                os.environ.get("BENCH_TRANSPORT_QUERIES", "3,5,21").split(",")
                if x.strip()]
            # legacy first: the first leg pays the cold XLA compiles, so
            # giving that to the BASELINE biases the ms ratios against the
            # new transports, never for them.  byte counts are exact either
            # way — they're the headline; ms is a raw corroborating ratio.
            legs = [
                ("legacy", {"ballista.shuffle.local.host_match": "false",
                            "ballista.shuffle.wire.streaming": "false"}),
                ("wire", {"ballista.shuffle.local.host_match": "false"}),
                ("mmap", {}),
            ]
            transport = result.setdefault("engine_transport", {})
            for leg, overrides in legs:
                if time.time() > deadline - 150:
                    transport[f"{leg}_skipped"] = "deadline"
                    break
                conf = {**base_config, **overrides}
                tmp = tempfile.mkdtemp(prefix=f"bench-transport-{leg}-")
                sched = SchedulerNetService(
                    "127.0.0.1", 0, config=BallistaConfig(dict(conf)))
                sched.start()
                executors = []
                try:
                    for i in range(2):
                        work = os.path.join(tmp, f"exec{i}")
                        os.makedirs(work)
                        ex = ExecutorServer(
                            "127.0.0.1", sched.port, "127.0.0.1", 0,
                            work_dir=work, concurrent_tasks=2,
                            executor_id=f"bench-{leg}-{i}",
                            config=BallistaConfig(dict(conf)))
                        ex.start()
                        executors.append(ex)
                    tctx = BallistaContext.remote(
                        "127.0.0.1", sched.port, BallistaConfig(dict(conf)))
                    try:
                        register_tables(tctx, DATA_DIR)
                        for q in transport_queries:
                            if time.time() > deadline - 100:
                                transport[f"q{q}_skipped"] = "deadline"
                                continue
                            s0 = dp.STATS.snapshot()
                            t0 = time.perf_counter()
                            res = tctx.sql(SQL[q]).collect()
                            wall = time.perf_counter() - t0
                            s1 = dp.STATS.snapshot()
                            rec = transport.setdefault(
                                f"q{q}_shuffle_transport", {})
                            rec[leg] = {
                                "ms": round(wall * 1000, 1),
                                "rows": sum(b.num_rows for b in res),
                                "local_bytes": (
                                    s1["bytes_fetched"]["local_mmap"]
                                    - s0["bytes_fetched"]["local_mmap"]
                                    + s1["bytes_fetched"]["local_copy"]
                                    - s0["bytes_fetched"]["local_copy"]),
                                "remote_bytes": (
                                    s1["bytes_fetched"]["remote"]
                                    - s0["bytes_fetched"]["remote"]),
                                "chunks": s1["chunks"] - s0["chunks"],
                                "raw_bytes": s1["raw_bytes"] - s0["raw_bytes"],
                                "wire_bytes": (s1["wire_bytes"]
                                               - s0["wire_bytes"]),
                            }
                            print(f"[worker] transport {leg} q{q}: "
                                  f"{wall*1000:.0f} ms "
                                  f"{json.dumps(rec[leg])}", file=sys.stderr)
                    finally:
                        tctx.shutdown()
                finally:
                    for ex in executors:
                        ex.stop(notify=False)
                    sched.stop()
                    shutil.rmtree(tmp, ignore_errors=True)
                emit(f"transport-{leg}")
            # headline deltas per query: wall-clock of the default path vs
            # the legacy wire, and bytes-on-wire of compressed streaming vs
            # whole-file (the remote series counts post-compression bytes)
            for q in transport_queries:
                rec = transport.get(f"q{q}_shuffle_transport")
                if not rec:
                    continue
                mmap_l, wire_l, legacy_l = (rec.get("mmap"), rec.get("wire"),
                                            rec.get("legacy"))
                if mmap_l and legacy_l and mmap_l["ms"]:
                    rec["legacy_over_mmap_ms"] = round(
                        legacy_l["ms"] / mmap_l["ms"], 3)
                if wire_l and legacy_l and wire_l["remote_bytes"]:
                    rec["legacy_over_wire_bytes"] = round(
                        legacy_l["remote_bytes"] / wire_l["remote_bytes"], 3)
            emit("transport-ab")
        except Exception as e:  # noqa: BLE001 — A/B leg must not kill the run
            result["engine_transport"] = {"error": f"{type(e).__name__}: {e}"}
            print(f"[worker] transport bench failed: {e}", file=sys.stderr)

    # --- serving leg: concurrent sessions, caches on vs off -------------
    # SF0.01 on purpose: per-query work is tiny so scheduler+planning
    # overhead — what the serving caches attack — dominates the off leg.
    # BENCH_SERVING=0 skips it; sessions/queries are env-tunable.
    if (os.environ.get("BENCH_SERVING", "1") != "0"
            and time.time() < deadline - 150):
        try:
            from benchmarks.serving import run_serving_benchmark

            result["serving"] = run_serving_benchmark(
                sessions=int(os.environ.get("BENCH_SERVING_SESSIONS", "32")),
                queries_per_session=int(
                    os.environ.get("BENCH_SERVING_QUERIES", "8")))
            sv = result["serving"]
            print(f"[worker] serving: {sv['on']['qps']} qps on vs "
                  f"{sv['off']['qps']} off "
                  f"({sv.get('qps_on_over_off', 0)}x), "
                  f"p99 q2l on={sv['on']['queue_to_launch_p99_ms']} ms "
                  f"off={sv['off']['queue_to_launch_p99_ms']} ms",
                  file=sys.stderr)
            emit("serving")
        except Exception as e:  # noqa: BLE001 — A/B leg must not kill the run
            result["serving"] = {"error": f"{type(e).__name__}: {e}"}
            print(f"[worker] serving bench failed: {e}", file=sys.stderr)

    # --- mesh path: same queries, ICI all_to_all shuffle ----------------
    # guarded end to end: a mesh-path failure must never discard the file
    # numbers already measured above
    if time.time() < deadline - 300:
        try:
            # min_rows=0: the default transport is ADAPTIVE (small exchanges
            # plan onto the file path), so the mesh leg forces mesh to keep
            # measuring the raw transport — the adaptive default is what
            # users get and equals the better of the two legs
            mesh_config = BallistaConfig(
                {**base_config, "ballista.shuffle.mesh": "true",
                 "ballista.shuffle.mesh.min_rows": "0"})
            result["mesh_forced"] = True
            mctx = BallistaContext.standalone(mesh_config, concurrent_tasks=4)
            try:
                register_tables(mctx, DATA_DIR)
                mesh_queries = [int(x) for x in MESH_QUERIES.split(",") if x.strip()]
                run_queries(mctx, mesh_queries, "mesh",
                            result.setdefault("engine_mesh", {}))
            finally:
                mctx.shutdown()
        except Exception as e:  # noqa: BLE001 — record, keep the file numbers
            result["engine_mesh"] = {"error": f"{type(e).__name__}: {e}"}
            print(f"[worker] mesh bench failed: {e}", file=sys.stderr)
    else:
        result["engine_mesh"] = {"skipped": "deadline"}

    # --- kernel: join shape (sorted-build + searchsorted probe) ---------
    # evidences the device join path: the build argsort is the one program
    # family measured to compile slowly on this backend, so compile time is
    # reported separately from steady-state
    if time.time() < deadline - 300:
        rngj = np.random.default_rng(11)
        n_probe, n_build = KERNEL_ROWS // 2, KERNEL_ROWS // 8
        pk = jax.device_put(jnp.asarray(
            rngj.integers(0, n_build * 2, n_probe).astype(np.int64)))
        bk = jax.device_put(jnp.asarray(np.arange(n_build, dtype=np.int64)))
        pmask_j = jax.device_put(jnp.ones(n_probe, bool))
        bmask_j = jax.device_put(jnp.ones(n_build, bool))
        out_cap = n_probe

        @jax.jit
        def join_step(pk, bk, pmask, bmask):
            bh_sorted, border, _ = K.build_side_sort([bk], bmask)
            ph = K.hash64([pk])
            pi, bp, pair_valid, total = K.probe_join(ph, pmask, bh_sorted, out_cap)
            bidx = border[bp]
            ok = pair_valid & bmask[bidx] & (pk[pi] == bk[bidx])
            return jnp.sum(ok), total

        t_c = time.perf_counter()
        jax.block_until_ready(join_step(pk, bk, pmask_j, bmask_j))
        result["kernel_join_compile_s"] = round(time.perf_counter() - t_c, 1)

        def _timed_join():
            out = join_step(pk, bk, pmask_j, bmask_j)
            jax.block_until_ready(out)
            np.asarray(out[0])  # scalar D2H: forces true remote completion

        medj = _med(_timed_join)
        result["kernel_join_rows_per_sec"] = round(n_probe / medj, 1)
        result["kernel_join_ms"] = round(medj * 1000, 3)
        print(f"[worker] kernel join: {n_probe/medj/1e6:.1f}M probe rows/s "
              f"({medj*1000:.2f} ms, compile {result['kernel_join_compile_s']}s)",
              file=sys.stderr)
        del pk, bk, pmask_j, bmask_j
        emit("kernel-join")

    emit("done")


# --------------------------------------------------------------------------
# parent orchestration
# --------------------------------------------------------------------------


class WorkerProc:
    """One supervised worker subprocess.  Non-blocking: the parent polls
    ``poll()`` which also harvests any new RESULT JSON lines from the
    worker's stdout file.  Full stdout/stderr is persisted win or lose
    (round-2 failure mode: only a 1500-char tail survived, losing the TPU
    kernel number that printed before the engine bench died)."""

    def __init__(self, platform: str, timeout: float, tag: str,
                 gate_file: str | None, deadline: float):
        self.platform = platform
        self.timeout = timeout
        env = dict(os.environ) if platform == "tpu" else _cpu_env()
        os.makedirs(LOG_DIR, exist_ok=True)
        stamp = int(time.time())
        self.log_path = os.path.join(LOG_DIR, f"attempt-{stamp}-{platform}{tag}.log")
        self.out_path = self.log_path + ".stdout"
        self.err_path = self.log_path + ".stderr"
        # the init watchdog can never exceed the attempt budget itself —
        # under a tight total budget a 600 s init allowance would let one
        # hung backend-init eat the whole run (the BENCH_r05 overrun)
        self.init_timeout = min(
            float(os.environ.get("BENCH_INIT_TIMEOUT", "600")),
            max(60.0, timeout - 30.0))
        self.t0 = time.time()
        self.timed_out: str | None = None
        self.result: dict | None = None
        self._out_pos = 0
        self._backend_up = platform != "tpu"
        cmd = [sys.executable, os.path.abspath(__file__), "--worker",
               "--platform", platform, "--deadline", str(deadline)]
        if gate_file:
            cmd += ["--gate-file", gate_file]
        self._out_fh = open(self.out_path, "w")
        self._err_fh = open(self.err_path, "w")
        self.proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                                     stdout=self._out_fh, stderr=self._err_fh,
                                     text=True)

    def _harvest(self) -> bool:
        """Read new stdout, keep the newest parseable JSON line.  Returns
        True when the result advanced."""
        advanced = False
        try:
            with open(self.out_path) as fh:
                fh.seek(self._out_pos)
                chunk = fh.read()
                self._out_pos = fh.tell()
        except OSError:
            return False
        for line in chunk.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    self.result = json.loads(line)
                    advanced = True
                except json.JSONDecodeError:
                    continue
        return advanced

    def poll(self) -> bool:
        """Advance supervision; True while still running."""
        self._harvest()
        if self.proc.poll() is not None:
            return False
        elapsed = time.time() - self.t0
        if not self._backend_up:
            try:
                with open(self.err_path) as fh:
                    self._backend_up = "backend up" in fh.read(65536)
            except OSError:
                pass
        if not self._backend_up and elapsed > self.init_timeout:
            self.timed_out = f"backend init exceeded {self.init_timeout:.0f}s"
        elif elapsed > self.timeout:
            self.timed_out = f"attempt exceeded {self.timeout:.0f}s"
        if self.timed_out:
            self.proc.kill()
            self.proc.wait()
            return False
        return True

    def finish(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self._harvest()
        for fh in (self._out_fh, self._err_fh):
            try:
                fh.close()
            except OSError:
                pass
        # errors='replace': a kill can truncate mid multi-byte character
        try:
            with open(self.out_path, errors="replace") as fh:
                stdout = fh.read()
            with open(self.err_path, errors="replace") as fh:
                stderr = fh.read()
            with open(self.log_path, "w") as fh:
                fh.write(f"# platform={self.platform} rc={self.proc.returncode} "
                         f"wall={time.time()-self.t0:.0f}s "
                         f"timed_out={self.timed_out}\n--- stdout ---\n{stdout}\n"
                         f"--- stderr ---\n{stderr}\n")
            for p in (self.out_path, self.err_path):
                os.remove(p)
            sys.stderr.write(stderr[-3000:])
        except OSError:
            pass
        print(f"[bench] {self.platform} worker done rc={self.proc.returncode} "
              f"timed_out={self.timed_out} log={self.log_path}", file=sys.stderr)


def _merge(cpu: dict | None, tpu: dict | None) -> dict:
    """The headline is TPU whenever the TPU worker measured ANY engine
    query; otherwise CPU with whatever TPU evidence exists attached."""
    tpu_has_engine = bool(tpu and (tpu.get("engine") or {}).get("q1_ms"))
    if tpu_has_engine:
        out = dict(tpu)
        if cpu:
            out["cpu"] = {k: v for k, v in cpu.items()
                          if k not in ("metric", "unit", "partial")}
        return out
    if cpu:
        out = dict(cpu)
        if tpu:
            out["tpu_partial"] = {k: v for k, v in tpu.items()
                                  if k not in ("metric", "unit")}
        return out
    if tpu:
        return dict(tpu)
    return {"metric": "tpch_q1_engine_rows_per_sec", "value": 0.0,
            "unit": "rows/s", "vs_baseline": 0.0, "error": "all attempts failed"}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--platform", default="auto")
    ap.add_argument("--gate-file", default=None)
    ap.add_argument("--deadline", type=float, default=0.0)
    args = ap.parse_args()

    if args.worker:
        deadline = args.deadline or (time.time() + 3600)
        _worker(args.platform, args.gate_file, deadline)
        return

    ensure_data()

    # default budget fits the 870 s tier-1 harness with margin (BENCH_r05
    # died at rc=124: the old 5400 s default let the TPU retry loop outlive
    # the external timeout even after the CPU worker had finished).  Longer
    # local runs: BENCH_TOTAL_TIMEOUT=5400 restores the old behavior.
    total_budget = float(os.environ.get("BENCH_TOTAL_TIMEOUT", "780"))
    tpu_budget = float(os.environ.get("BENCH_TPU_TIMEOUT", str(total_budget - 120)))
    cpu_budget = float(os.environ.get("BENCH_CPU_TIMEOUT",
                                      str(total_budget - 60)))
    t_start = time.time()
    hard_deadline = t_start + total_budget
    os.makedirs(LOG_DIR, exist_ok=True)
    gate_file = os.path.join(LOG_DIR, f"gate-{int(t_start)}")

    want_tpu = args.platform in ("auto", "tpu")
    want_cpu = args.platform in ("auto", "cpu")

    cpu_w = WorkerProc("cpu", cpu_budget, "-0", None, hard_deadline - 30) \
        if want_cpu else None
    if not want_cpu:
        # no CPU worker: open the gate immediately
        open(gate_file, "w").close()
    tpu_w = WorkerProc("tpu", tpu_budget, "-0", gate_file,
                       hard_deadline - 30) if want_tpu else None

    cpu_result: dict | None = None
    tpu_result: dict | None = None
    last_emitted = None
    tpu_attempt = 0
    tpu_give_up = False

    def emit_best() -> None:
        nonlocal last_emitted
        merged = _merge(cpu_result, tpu_result)
        line = json.dumps(merged)
        if line != last_emitted:
            last_emitted = line
            print(line, flush=True)
            try:
                with open(os.path.join(LOG_DIR, "latest.json"), "w") as fh:
                    fh.write(line + "\n")
            except OSError:
                pass

    while time.time() < hard_deadline:
        busy = False
        if cpu_w is not None:
            if cpu_w.poll():
                busy = True
            else:
                cpu_w.finish()
                cpu_result = cpu_w.result or cpu_result
                cpu_w = None
                open(gate_file, "w").close()  # release the TPU engine phase
                emit_best()
        if tpu_w is not None:
            if tpu_w.poll():
                busy = True
                if tpu_w.result is not None and tpu_w.result != tpu_result:
                    tpu_result = tpu_w.result
                    emit_best()
            else:
                tpu_w.finish()
                tpu_result = tpu_w.result or tpu_result
                finished_ok = (tpu_w.timed_out is None
                               and tpu_w.proc.returncode == 0)
                made_progress = tpu_w.result is not None
                tpu_w = None
                emit_best()
                # retry while budget remains, UNLESS the worker finished its
                # run (rc=0) or got far enough that a rerun can't do better
                remaining = t_start + tpu_budget - time.time()
                if (want_tpu and not finished_ok and not made_progress
                        and not tpu_give_up and remaining > 300):
                    tpu_attempt += 1
                    backoff = min(120.0, 30.0 * tpu_attempt)
                    print(f"[bench] tpu retry #{tpu_attempt} in {backoff:.0f}s "
                          f"({remaining:.0f}s budget left)", file=sys.stderr)
                    time.sleep(backoff)
                    tpu_w = WorkerProc("tpu", t_start + tpu_budget - time.time(),
                                       f"-{tpu_attempt}", gate_file,
                                       hard_deadline - 30)
                else:
                    tpu_give_up = True
        if cpu_w is None and tpu_w is None:
            break
        if busy:
            time.sleep(5)

    for w in (cpu_w, tpu_w):
        if w is not None:
            w.finish()
            if w.platform == "cpu":
                cpu_result = w.result or cpu_result
            else:
                tpu_result = w.result or tpu_result
    # final merged line is ALWAYS the last stdout line
    last_emitted = None
    emit_best()


if __name__ == "__main__":
    main()
