"""Headline benchmark: TPC-H through the engine on one chip.

Two layers, both reported:

- **engine**: TPC-H q1 + q6 at SF1 run END-TO-END through
  ``BallistaContext.standalone`` — parquet scan -> device pipeline ->
  shuffle -> final aggregate -> collect.  The headline metric is engine
  rows/s on q1 (lineitem rows / wall-clock), matching how the reference's
  README chart is computed (reference README.md:52-60: q1 SF10 in ~3.1 s on
  a 24-core executor => ~19.35M rows/s, see BASELINE.md).
- **kernel**: the fused q1 pipeline (filter -> derived columns -> grouped
  aggregate) over HBM-resident arrays, isolating device throughput from IO.

Robustness (round-1 failure mode: the experimental "axon" TPU plugin can
fail or hang at backend init): the parent process never imports jax.  It
launches a worker subprocess per attempt — TPU with retries, then a
CPU-forced fallback — with a hard timeout, and re-prints the worker's final
JSON line.  Exactly ONE JSON line lands on stdout:
  {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N, ...}
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_ROWS_PER_S = 59_986_052 / 3.1  # reference q1 SF10 wall-clock
SCALE = float(os.environ.get("BENCH_SCALE", "1"))
QUERIES = os.environ.get("BENCH_QUERIES", "1,6")
DATA_DIR = os.environ.get(
    "BENCH_DATA", os.path.join(REPO, ".bench_data", f"tpch-sf{SCALE:g}")
)
KERNEL_ROWS = int(os.environ.get("BENCH_KERNEL_ROWS", str(8_000_000)))


def _cpu_env(n_devices: int = 1) -> dict:
    # single definition of "CPU-forced, TPU-plugin-free" lives next to the
    # other driver entry point
    sys.path.insert(0, REPO)
    from __graft_entry__ import _scrubbed_cpu_env

    return _scrubbed_cpu_env(n_devices)


def ensure_data() -> None:
    marker = os.path.join(DATA_DIR, "lineitem.parquet")
    if os.path.exists(marker):
        return
    os.makedirs(DATA_DIR, exist_ok=True)
    print(f"[bench] generating TPC-H SF{SCALE:g} under {DATA_DIR}", file=sys.stderr)
    subprocess.run(
        [sys.executable, "-m", "benchmarks.tpch", "convert",
         "--scale", str(SCALE), "--output", DATA_DIR],
        cwd=REPO, env=_cpu_env(), check=True, timeout=1800,
        stdout=sys.stderr,
    )


# --------------------------------------------------------------------------
# worker (runs in a subprocess; the only place jax is imported)
# --------------------------------------------------------------------------


def _worker(platform: str) -> None:
    import numpy as np
    import jax

    # int64 columns (fixed-point decimals, keys) need x64; the device path
    # never produces f64 arrays (divisions are host-finalize), so this is
    # TPU-safe
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"[worker] backend up: {dev.platform} ({dev.device_kind})", file=sys.stderr)

    detail: dict = {"platform": dev.platform, "device": str(dev.device_kind)}

    # --- platform characterization: the constants needed to interpret the
    # engine numbers (the device may sit across a network tunnel where
    # per-op latency, not FLOPs, dominates) -----------------------------
    def _med(f, n=5):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            f()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    small = np.zeros(128, np.int32)
    big = np.zeros(8 << 20, np.int64)  # 64 MB
    d_small = jax.device_put(small)
    jax.block_until_ready(d_small)
    tiny = jax.jit(lambda x: x + 1)
    jax.block_until_ready(tiny(d_small))
    rtt = _med(lambda: jax.block_until_ready(tiny(d_small)))
    h2d = _med(lambda: jax.block_until_ready(jax.device_put(big)), 3)
    # d2h must use a FRESH device array per iteration: ArrayImpl caches the
    # first host copy (_npy_value), so re-reading the same array measures a
    # cache hit, not the transfer
    d_bigs = [jax.device_put(tiny(jax.device_put(big))) for _ in range(3)]
    jax.block_until_ready(d_bigs)
    it = iter(d_bigs)
    d2h = _med(lambda: np.asarray(next(it)), 3)
    detail["platform_rtt_ms"] = round(rtt * 1000, 2)
    detail["platform_h2d_gbps"] = round(big.nbytes / h2d / 1e9, 2)
    detail["platform_d2h_gbps"] = round(big.nbytes / d2h / 1e9, 2)
    print(f"[worker] platform: rtt {rtt*1000:.2f} ms, "
          f"h2d {big.nbytes/h2d/1e9:.2f} GB/s, d2h {big.nbytes/d2h/1e9:.2f} GB/s",
          file=sys.stderr)
    del d_bigs, big

    # --- kernel microbench ---------------------------------------------
    sys.path.insert(0, REPO)
    from __graft_entry__ import _q1_augment, _q1_example, _q1_filter, _Q1_AGGS, _Q1_KEYS
    from arrow_ballista_tpu.ops import kernels as K

    cols_np, mask_np = _q1_example(KERNEL_ROWS, seed=7)
    cols = {k: jax.device_put(jnp.asarray(v)) for k, v in cols_np.items()}
    mask = jax.device_put(jnp.asarray(mask_np))

    # key_ranges mirrors the engine: returnflag/linestatus are dict-coded
    # strings with host-known code ranges, which selects the dense sort-free
    # grouping path (kernels.grouped_aggregate) — the path engine q1 runs
    @jax.jit
    def step(cols, mask):
        cols, mask = _q1_filter(cols, mask)
        cols = _q1_augment(cols)
        keys = [cols[k] for k in _Q1_KEYS]
        vals = [(cols[v], how) for v, how in _Q1_AGGS]
        return K.grouped_aggregate(keys, vals, mask, 16,
                                   key_ranges=((0, 2), (0, 1)))

    t_c = time.perf_counter()
    out = step(cols, mask)  # compile + warmup
    jax.block_until_ready(out)
    detail["kernel_q1_compile_s"] = round(time.perf_counter() - t_c, 1)
    # block on the WHOLE output tree AND force a 1-element host read: an
    # experimental remote backend's block_until_ready may not await remote
    # completion, and a D2H read cannot lie (its cost is one rtt, reported
    # above for subtraction)
    def _timed_step():
        out = step(cols, mask)
        jax.block_until_ready(out)
        # tiny D2H read (16-slot group mask): completion proof — overflow
        # (out[3]) is None on the dense path since it became statically
        # impossible there
        np.asarray(out[2])

    med = _med(_timed_step, 10)
    kernel_rows_s = KERNEL_ROWS / med
    # sanity companion: effective HBM read bandwidth implied by the input
    # columns alone — if this exceeds the chip's spec the measurement is
    # wrong, not the kernel fast
    in_bytes = sum(v.nbytes for v in cols.values()) + mask.nbytes
    detail["kernel_q1_rows_per_sec"] = round(kernel_rows_s, 1)
    detail["kernel_q1_ms"] = round(med * 1000, 3)
    detail["kernel_q1_gbps"] = round(in_bytes / med / 1e9, 1)
    print(f"[worker] kernel q1: {kernel_rows_s/1e6:.1f}M rows/s "
          f"({med*1000:.2f} ms, {in_bytes/med/1e9:.0f} GB/s implied)",
          file=sys.stderr)
    del cols, mask, out

    # --- engine bench: TPC-H through BallistaContext --------------------
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.config import BallistaConfig
    from benchmarks.queries import QUERIES as SQL
    from benchmarks.tpch import register_tables

    # ONE base config shared by the file and mesh runs so the two transports
    # stay knob-for-knob comparable
    base_config = {
        # auto -> ceil(rows/batch) partitions; measured best on SF1 (6 for
        # the 12-row-group lineitem: 2 row groups per scan task)
        "ballista.shuffle.partitions": "auto",
        "ballista.batch.size": str(1 << 20),
        # engine deadline: generous (slow first-compile runs must finish) but
        # below the parent's subprocess timeout so the engine fails first
        # with a real error instead of a SIGKILL
        "ballista.job.timeout.seconds": "1800",
    }
    def _warm_cache(paths, label):
        # warm the OS page cache first: whichever run goes first would
        # otherwise pay cold disk reads the others don't (observed: file
        # q1 7.3 s cold vs 3.0 s warm on the same code)
        t_w = time.perf_counter()
        for path in paths:
            with open(path, "rb") as fh:
                while fh.read(1 << 24):
                    pass
        print(f"[worker] {label} page-cache warmup: "
              f"{time.perf_counter()-t_w:.1f}s", file=sys.stderr)

    _warm_cache([os.path.join(DATA_DIR, f)
                 for f in sorted(os.listdir(DATA_DIR))
                 if f.endswith(".parquet")], "sf1")

    ctx = BallistaContext.standalone(BallistaConfig(dict(base_config)),
                                     concurrent_tasks=4)
    register_tables(ctx, DATA_DIR)
    lineitem_rows = ctx.catalog.provider("lineitem").row_count()
    detail["lineitem_rows"] = lineitem_rows

    def _job_metrics(ctx):
        """Aggregate per-operator metrics of the most recent job, per stage —
        every bench run doubles as a profile (the round-2 lesson: a failed
        run with no metrics tells you nothing about WHERE the time went)."""
        try:
            sched = ctx._standalone.scheduler
            jobs = list(sched.jobs._status)
            if not jobs:
                return {}
            graph = sched.jobs.get_graph(jobs[-1])
            out = {}
            for sid in sorted(graph.stages):
                s = graph.stages[sid]
                spans = []
                for t in s.task_infos:
                    if not t or not t.status:
                        continue
                    st = t.status
                    if st.start_time_ms and st.end_time_ms:
                        spans.append((st.start_time_ms, st.end_time_ms))
                entry = {k: round(v, 2)
                         for k, v in sorted(s.aggregate_metrics().items())
                         if v >= 0.05}
                if spans:
                    entry["stage_wall_s"] = round(
                        (max(b for _, b in spans) - min(a for a, _ in spans))
                        / 1000, 2)
                out[f"stage{sid}"] = entry
            return out
        except Exception as e:  # noqa: BLE001 — profiling must never kill a bench
            return {"error": str(e)}

    def run_queries(ctx, queries, label):
        out = {}
        for q in queries:
            per = []
            try:
                for it in range(2):
                    t0 = time.perf_counter()
                    res = ctx.sql(SQL[q]).collect()
                    nrows = sum(b.num_rows for b in res)
                    per.append(time.perf_counter() - t0)
                    print(f"[worker] {label} q{q} iter{it}: {per[-1]*1000:.0f} ms "
                          f"({nrows} rows)", file=sys.stderr)
                out[f"q{q}_ms"] = round(min(per) * 1000, 1)
                print(f"[worker] {label} q{q} metrics: "
                      f"{json.dumps(_job_metrics(ctx))}", file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — record, keep benching
                out[f"q{q}_error"] = f"{type(e).__name__}: {e}"
                print(f"[worker] {label} q{q} FAILED: {e}", file=sys.stderr)
        return out

    # q3 rides along on BOTH transports so the join paths are comparable
    # (round-2 gap: the mesh join had zero perf evidence; a mesh-only q3
    # number answers nothing without the file-path number next to it)
    queries = [int(x) for x in QUERIES.split(",")]
    if 3 not in queries:
        queries = queries + [3]
    engine = run_queries(ctx, queries, "file")
    ctx.shutdown()
    detail["engine"] = engine

    # --- mesh path: same queries + a join shape, ICI all_to_all shuffle ---
    # guarded end to end: a mesh-path failure must never discard the file
    # numbers already measured above
    try:
        mesh_config = BallistaConfig(
            {**base_config, "ballista.shuffle.mesh": "true"})
        mctx = BallistaContext.standalone(mesh_config, concurrent_tasks=4)
        try:
            register_tables(mctx, DATA_DIR)
            detail["engine_mesh"] = run_queries(mctx, queries, "mesh")
        finally:
            mctx.shutdown()
    except Exception as e:  # noqa: BLE001 — record, keep the file numbers
        detail["engine_mesh"] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[worker] mesh bench failed: {e}", file=sys.stderr)

    q1_s = engine.get("q1_ms", 0.0) / 1000.0
    value = lineitem_rows / q1_s if q1_s else 0.0
    result = {
        "metric": f"tpch_q1_sf{SCALE:g}_engine_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(value / BASELINE_ROWS_PER_S, 4),
        **detail,
    }
    if not q1_s:
        # a 0.0 headline must be distinguishable from a measured zero
        result["error"] = ("q1 not measured: " +
                           engine.get("q1_error", "not in BENCH_QUERIES"))
    # provisional print FIRST: the parent takes the LAST parseable JSON
    # line, so if anything below (join microbench compile, SF10 rider)
    # outlives the attempt budget and the worker is killed, the SF1
    # headline already on stdout still wins.  The join kernel moved AFTER
    # this print for exactly that reason: its fresh-shape build argsort
    # compile once wedged the remote compile helper for 25+ minutes and
    # starved the whole attempt of engine numbers.
    print(json.dumps(result), flush=True)

    # --- kernel: join shape (sorted-build + searchsorted probe) ---------
    # evidences the device join path: the build argsort is the one program
    # family measured to compile slowly on this backend, so compile time is
    # reported separately from steady-state
    rngj = np.random.default_rng(11)
    n_probe, n_build = KERNEL_ROWS // 2, KERNEL_ROWS // 8
    pk = jax.device_put(jnp.asarray(
        rngj.integers(0, n_build * 2, n_probe).astype(np.int64)))
    bk = jax.device_put(jnp.asarray(np.arange(n_build, dtype=np.int64)))
    pmask_j = jax.device_put(jnp.ones(n_probe, bool))
    bmask_j = jax.device_put(jnp.ones(n_build, bool))
    out_cap = n_probe

    @jax.jit
    def join_step(pk, bk, pmask, bmask):
        bh_sorted, border, _ = K.build_side_sort([bk], bmask)
        ph = K.hash64([pk])
        pi, bp, pair_valid, total = K.probe_join(ph, pmask, bh_sorted, out_cap)
        bidx = border[bp]
        ok = pair_valid & bmask[bidx] & (pk[pi] == bk[bidx])
        return jnp.sum(ok), total

    t_c = time.perf_counter()
    jax.block_until_ready(join_step(pk, bk, pmask_j, bmask_j))
    detail["kernel_join_compile_s"] = round(time.perf_counter() - t_c, 1)

    def _timed_join():
        out = join_step(pk, bk, pmask_j, bmask_j)
        jax.block_until_ready(out)
        np.asarray(out[0])  # scalar D2H: forces true remote completion

    medj = _med(_timed_join)
    result["kernel_join_rows_per_sec"] = round(n_probe / medj, 1)
    result["kernel_join_ms"] = round(medj * 1000, 3)
    result["kernel_join_compile_s"] = detail["kernel_join_compile_s"]
    print(f"[worker] kernel join: {n_probe/medj/1e6:.1f}M probe rows/s "
          f"({medj*1000:.2f} ms, compile {detail['kernel_join_compile_s']}s)",
          file=sys.stderr)
    del pk, bk, pmask_j, bmask_j
    print(json.dumps(result), flush=True)

    # --- SF10 rider: q1 when the data exists ----------------------------
    # the reference baseline IS SF10 (README.md:52-60); this records the
    # like-for-like datapoint whenever a prior round generated the data,
    # without making the headline depend on a 13-minute generation step
    sf10_dir = os.path.join(REPO, ".bench_data", "tpch-sf10")
    if SCALE == 1 and os.path.exists(os.path.join(sf10_dir, "lineitem.parquet")):
        try:
            _warm_cache([os.path.join(sf10_dir, "lineitem.parquet")], "sf10")
            ctx10 = BallistaContext.standalone(
                BallistaConfig(dict(base_config)), concurrent_tasks=4)
            try:
                register_tables(ctx10, sf10_dir)
                rows10 = ctx10.catalog.provider("lineitem").row_count()
                sf10 = run_queries(ctx10, [1], "sf10")
                q1_10 = sf10.get("q1_ms", 0.0) / 1000.0
                if q1_10:
                    sf10["q1_rows_per_sec"] = round(rows10 / q1_10, 1)
                    sf10["vs_baseline_sf10"] = round(
                        rows10 / q1_10 / BASELINE_ROWS_PER_S, 4)
                    # the reference baseline IS SF10 (README.md:52-60):
                    # when the like-for-like datapoint exists it becomes
                    # the headline; the SF1 numbers stay in `engine`
                    result["metric"] = "tpch_q1_sf10_engine_rows_per_sec"
                    result["value"] = sf10["q1_rows_per_sec"]
                    result["vs_baseline"] = sf10["vs_baseline_sf10"]
                result["engine_sf10"] = sf10
            finally:
                ctx10.shutdown()
        except Exception as e:  # noqa: BLE001 — rider must not kill the run
            result["engine_sf10"] = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(result))


# --------------------------------------------------------------------------
# parent orchestration
# --------------------------------------------------------------------------


LOG_DIR = os.path.join(REPO, ".bench_logs")


def _attempt(platform: str, timeout: int, tag: str = ""):
    """Run one worker subprocess.  The FULL stdout/stderr is persisted to a
    log file win or lose (round-2 failure mode: only a 1500-char tail
    survived, losing the TPU kernel number that printed before the engine
    bench died).

    Backend-init watchdog: the experimental TPU plugin's tunnel grant can
    wedge for an hour+ (observed), hanging jax.devices() with zero CPU.
    The worker prints '[worker] backend up' the moment the backend exists;
    if that marker hasn't appeared within BENCH_INIT_TIMEOUT the attempt
    is killed early so a wedged tunnel can't eat the whole bench budget —
    the CPU fallback still produces a number."""
    env = dict(os.environ) if platform == "tpu" else _cpu_env()
    os.makedirs(LOG_DIR, exist_ok=True)
    stamp = int(time.time())
    log_path = os.path.join(LOG_DIR, f"attempt-{stamp}-{platform}{tag}.log")
    out_path = log_path + ".stdout"
    err_path = log_path + ".stderr"
    init_timeout = int(os.environ.get("BENCH_INIT_TIMEOUT", "900"))
    t0 = time.time()
    timed_out = None
    with open(out_path, "w") as out_fh, open(err_path, "w") as err_fh:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--platform", platform],
            cwd=REPO, env=env, stdout=out_fh, stderr=err_fh, text=True,
        )
        backend_up = platform != "tpu"
        while proc.poll() is None:
            time.sleep(5)
            elapsed = time.time() - t0
            if not backend_up:
                try:
                    with open(err_path) as fh:
                        backend_up = "backend up" in fh.read(65536)
                except OSError:
                    pass
            if not backend_up and elapsed > init_timeout:
                timed_out = f"backend init exceeded {init_timeout}s"
                break
            if elapsed > timeout:
                timed_out = f"attempt exceeded {timeout}s"
                break
        if timed_out is not None:
            proc.kill()
            proc.wait()
    rc = -1 if timed_out else proc.returncode
    # errors='replace': a kill can truncate mid multi-byte character, and a
    # decode crash here would abort the bench instead of falling back
    with open(out_path, errors="replace") as fh:
        stdout = fh.read()
    with open(err_path, errors="replace") as fh:
        stderr = fh.read()
    with open(log_path, "w") as fh:
        fh.write(f"# platform={platform} rc={rc} wall={time.time()-t0:.0f}s "
                 f"timed_out={timed_out}\n--- stdout ---\n{stdout}\n"
                 f"--- stderr ---\n{stderr}\n")
    for p in (out_path, err_path):
        try:
            os.remove(p)
        except OSError:
            pass
    print(f"[bench] full log: {log_path}", file=sys.stderr)
    if timed_out:
        print(f"[bench] {platform} attempt killed: {timed_out}", file=sys.stderr)
        return None
    sys.stderr.write(stderr[-4000:])
    if rc != 0:
        print(f"[bench] {platform} attempt failed rc={rc} "
              f"after {time.time()-t0:.0f}s", file=sys.stderr)
        return None
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"[bench] {platform} attempt produced no JSON", file=sys.stderr)
    return None


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--platform", default="auto")
    args = ap.parse_args()

    if args.worker:
        _worker(args.platform)
        return

    ensure_data()

    # subprocess timeout must exceed the engine's own job deadline (the
    # worker sets ballista.job.timeout.seconds below it) so a slow-but-alive
    # TPU run is never SIGKILLed from outside
    tpu_budget = int(os.environ.get("BENCH_TPU_TIMEOUT", "3600"))
    plan = []
    if args.platform in ("auto", "tpu"):
        plan += [("tpu", tpu_budget)]
    if args.platform in ("auto", "cpu"):
        plan += [("cpu", 2400)]

    result = None
    for i, (platform, timeout) in enumerate(plan):
        if i > 0:
            time.sleep(20)
        t0 = time.time()
        result = _attempt(platform, timeout, tag=f"-{i}")
        if result is None and platform == "tpu" and time.time() - t0 < 300:
            # fast failure = transient backend-init Unavailable (device-grant
            # tunnel recovering), not a slow run: one fresh retry is cheap
            # and often succeeds.  Slow failures are NOT retried — a second
            # identical attempt can only fail the same way (round-2 lesson).
            time.sleep(20)
            result = _attempt(platform, timeout, tag=f"-{i}-retry")
        if result is not None:
            break
    if result is None:
        result = {"metric": "tpch_q1_engine_rows_per_sec", "value": 0.0,
                  "unit": "rows/s", "vs_baseline": 0.0, "error": "all attempts failed"}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
